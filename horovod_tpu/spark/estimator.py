"""Estimator API over spark.run (ref: horovod/spark/keras/estimator.py,
horovod/spark/torch/estimator.py — fit framework models on DataFrames).

`JaxEstimator.fit(df)` trains a flax model data-parallel across Spark
tasks: the DataFrame's feature/label columns are collected per
partition, each task trains on its shard with grads allreduced through
the engine, and rank 0's params come back in a `JaxModel` transformer.
Works with pandas DataFrames directly for local use.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np


class JaxModel:
    """Fitted-model transformer (ref: spark estimators' Model)."""

    def __init__(self, model, params, feature_cols, label_col, output_col):
        self.model = model
        self.params = params
        self.feature_cols = feature_cols
        self.label_col = label_col
        self.output_col = output_col

    def transform(self, df):
        import pandas as pd

        pdf = df.toPandas() if hasattr(df, "toPandas") else df
        x = np.stack([pdf[c].to_numpy() for c in self.feature_cols], axis=-1)
        out = np.asarray(self.model.apply(self.params, x))
        res = pdf.copy()
        res[self.output_col] = list(out)
        return res


class JaxEstimator:
    """(ref: estimator params subset — model, optimizer, loss, epochs,
    batch_size, feature/label cols.)"""

    def __init__(
        self,
        model,
        optimizer,
        loss: Callable,
        feature_cols: Sequence[str],
        label_col: str,
        output_col: str = "prediction",
        num_proc: Optional[int] = None,
        epochs: int = 1,
        batch_size: int = 32,
        seed: int = 0,
    ):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_col = label_col
        self.output_col = output_col
        self.num_proc = num_proc
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed

    # ------------------------------------------------------------------
    def _collect(self, df):
        pdf = df.toPandas() if hasattr(df, "toPandas") else df
        x = np.stack(
            [pdf[c].to_numpy() for c in self.feature_cols], axis=-1
        ).astype(np.float32)
        y = pdf[self.label_col].to_numpy()
        return x, y

    def fit(self, df) -> JaxModel:
        x, y = self._collect(df)
        est = self

        def train():
            import jax
            import optax

            import horovod_tpu as hvd

            hvd.init()
            xs = x[hvd.rank()::hvd.size()]
            ys = y[hvd.rank()::hvd.size()]
            params = est.model.init(
                jax.random.PRNGKey(est.seed), xs[: est.batch_size]
            )
            params = hvd.broadcast_parameters(params, root_rank=0)
            tx = hvd.DistributedOptimizer(est.optimizer)
            opt_state = tx.init(params)

            grad_fn = jax.jit(jax.value_and_grad(
                lambda p, bx, by: est.loss(est.model.apply(p, bx), by)
            ))
            steps = max(len(xs) // est.batch_size, 1)
            for epoch in range(est.epochs):
                perm = np.random.RandomState(epoch).permutation(len(xs))
                for i in range(steps):
                    idx = perm[i * est.batch_size:(i + 1) * est.batch_size]
                    if len(idx) == 0:
                        break
                    _, grads = grad_fn(params, xs[idx], ys[idx])
                    upd, opt_state = tx.update(grads, opt_state, params)
                    params = optax.apply_updates(params, upd)
            return jax.tree.map(np.asarray, params)

        num_proc = self.num_proc or 1
        if hasattr(df, "rdd") or num_proc > 1:
            results = self._run_distributed(train, num_proc, df)
        else:
            results = [train()]
        return JaxModel(self.model, results[0], self.feature_cols,
                        self.label_col, self.output_col)

    def _run_distributed(self, train, num_proc, df):
        if hasattr(df, "rdd"):
            from .runner import run as spark_run

            return spark_run(train, num_proc=num_proc)
        from ..runner import run as local_run

        return local_run(train, np=num_proc)
