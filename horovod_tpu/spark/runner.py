"""horovod_tpu.spark.run — launch distributed training inside Spark
tasks (ref: horovod/spark/runner.py:195-301 run / :303 run_elastic).

Orchestration (mirrors the reference's shape):
  1. driver starts a rendezvous/KV server;
  2. one Spark task per rank executes `_task_fn` (barrier-stage
     semantics when available): each task registers its host, receives
     its slot assignment, sets the HOROVOD_* env, runs the user fn, and
     ships the pickled result back through the KV;
  3. results return in rank order.

The Spark interaction is confined to `_mapper` + `_run_spark_job`, so
the orchestration is testable without a cluster (tests inject a mock
SparkContext) and any pyspark ≥2.4 works at runtime.
"""
from __future__ import annotations

import os
import pickle
import socket
import uuid
from typing import Any, Callable, Dict, List, Optional

from ..runner.hosts import HostInfo, get_host_assignments
from ..runner.rendezvous_server import RendezvousServer
from ..utils.logging import get_logger

logger = get_logger()
from ..utils import env as env_cfg


def _driver_addr() -> str:
    return os.environ.get("HVDRUN_DRIVER_ADDR") or socket.gethostname()


def _task_fn(index: int, driver_addr: str, driver_port: int,
             payload: bytes, extra_env: Dict[str, str]):
    """Runs inside the Spark executor (ref: horovod/spark/task/)."""
    from ..backend.rendezvous import RendezvousClient

    client = RendezvousClient(driver_addr, driver_port, timeout=300.0)
    client.put("spark_hosts", str(index), socket.gethostname().encode())
    # Driver computes assignments once all tasks registered.
    row = client.wait_get("spark_assign", str(index)).decode()
    rank, size, lrank, lsize, crank, csize = (int(v) for v in row.split(","))
    env = {
        env_cfg.RANK: str(rank),
        env_cfg.SIZE: str(size),
        env_cfg.LOCAL_RANK: str(lrank),
        env_cfg.LOCAL_SIZE: str(lsize),
        env_cfg.CROSS_RANK: str(crank),
        env_cfg.CROSS_SIZE: str(csize),
        env_cfg.RENDEZVOUS_ADDR: driver_addr,
        env_cfg.RENDEZVOUS_PORT: str(driver_port),
        env_cfg.CONTROLLER: "tcp",
        env_cfg.CPU_OPERATIONS: "tcp",
    }
    env.update(extra_env)
    os.environ.update(env)
    fn = pickle.loads(payload)
    result = fn()
    client.put("spark_results", str(rank), pickle.dumps(result))
    return rank


def _assign_ranks(server: RendezvousServer, num_proc: int):
    """Group registered tasks by host-hash into the reference's
    rank/local/cross topology (ref: spark/runner.py:230-260 host-hash
    grouping)."""
    by_host: Dict[str, List[int]] = {}
    order: List[int] = []
    for i in range(num_proc):
        host = server.handle_get(f"spark_hosts/{i}")
        host = host.decode() if host else f"unknown-{i}"
        by_host.setdefault(host, []).append(i)
        order.append(i)
    hosts = [HostInfo(h, len(idxs)) for h, idxs in by_host.items()]
    slots = get_host_assignments(hosts, num_proc, num_proc)
    # Map slot -> task index: the k-th task on a host takes that host's
    # k-th slot.
    it = {h: list(idxs) for h, idxs in by_host.items()}
    for slot in slots:
        task_index = it[slot.hostname].pop(0)
        server.handle_put(
            f"spark_assign/{task_index}", slot.to_response_string().encode()
        )


def _run_spark_job(sc, num_proc: int, mapper, barrier: bool = True):
    """Execute mapper over num_proc partitions, barrier-mode when the
    cluster supports it (ref: spark/runner.py barrier usage).

    The ELASTIC path passes barrier=False: a barrier stage gang-
    schedules (no task starts until all max_np fit, defeating the
    min_np window) and aborts every task on a single death (defeating
    shrink-and-continue). The reference's run_elastic likewise runs a
    plain stage."""
    rdd = sc.parallelize(range(num_proc), num_proc)
    if barrier:
        try:
            return rdd.barrier().mapPartitionsWithIndex(mapper).collect()
        except AttributeError:  # pre-2.4 or mock without barrier
            pass
    return rdd.mapPartitionsWithIndex(mapper).collect()


def run(
    fn: Callable[[], Any],
    args=(),
    kwargs=None,
    num_proc: Optional[int] = None,
    extra_env: Optional[Dict[str, str]] = None,
    verbose: int = 1,
    spark_context=None,
) -> List[Any]:
    """Run `fn` on `num_proc` Spark tasks; per-rank results in rank order
    (ref: horovod/spark/runner.py:195 signature subset)."""
    import functools

    try:
        import cloudpickle as pickler
    except ImportError:
        pickler = pickle

    sc = spark_context
    if sc is None:
        try:
            from pyspark import SparkContext

            sc = SparkContext._active_spark_context
        except ImportError as e:
            raise ImportError(
                "horovod_tpu.spark.run needs pyspark (or pass "
                "spark_context=); for non-Spark clusters use "
                "horovod_tpu.runner.run"
            ) from e
        if sc is None:
            raise ValueError("no active SparkContext")
    if num_proc is None:
        num_proc = sc.defaultParallelism

    payload = pickler.dumps(functools.partial(fn, *args, **(kwargs or {})))
    server = RendezvousServer()
    port = server.start()
    addr = _driver_addr()
    env = dict(extra_env or {})
    # Same platform-leak guard as runner.run(): Spark task workers fork
    # from a driver that may hold a single tunneled accelerator they
    # cannot re-register; default them to CPU unless the caller opts in.
    if "JAX_PLATFORMS" not in env:
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("PALLAS_AXON_POOL_IPS", "")

    # Driver-side assignment thread: wait for all registrations, then
    # publish the topology rows.
    import threading

    def assigner():
        import time

        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            if all(
                server.handle_get(f"spark_hosts/{i}") is not None
                for i in range(num_proc)
            ):
                _assign_ranks(server, num_proc)
                return
            time.sleep(0.1)

    t = threading.Thread(target=assigner, daemon=True)
    t.start()

    def mapper(index, iterator):
        yield _task_fn(index, addr, port, payload, env)

    try:
        _run_spark_job(sc, num_proc, mapper)
        results = []
        for r in range(num_proc):
            blob = server.handle_get(f"spark_results/{r}")
            if blob is None:
                raise RuntimeError(f"rank {r} produced no result")
            results.append(pickle.loads(blob))
        return results
    finally:
        server.stop()


def run_elastic(
    fn: Callable[[], Any],
    args=(),
    kwargs=None,
    num_proc: Optional[int] = None,
    min_np: Optional[int] = None,
    max_np: Optional[int] = None,
    extra_env: Optional[Dict[str, str]] = None,
    reset_limit: Optional[int] = None,
    verbose: int = 1,
    spark_context=None,
    start_timeout: float = 600.0,
) -> List[Any]:
    """Elastic training over Spark tasks with a live min_np..max_np
    window (ref: horovod/spark/runner.py:303-404).

    `max_np` Spark tasks are launched (a plain, NON-barrier stage:
    tasks start as the cluster can schedule them, so the job begins as
    soon as `min_np` are live); each runs a task-service loop
    (`spark/elastic.py`) that heartbeats and executes worker
    spawn/kill commands from the in-driver ElasticDriver. A task dying
    mid-job shrinks the world (down to `min_np`); a task (re)appearing
    grows it — with `hvd.elastic.run` + State inside `fn` carrying
    training through each reset, exactly like host-discovery elastic
    under `hvdrun`. Results are per-rank values from the FINAL topology,
    rank order.

    `num_proc` is only the default for an unset min_np/max_np (the
    reference reads dynamic-allocation bounds the same way,
    ref: spark/runner.py:355-360); the window is what governs."""
    import functools
    import threading

    try:
        import cloudpickle as pickler
    except ImportError:
        pickler = pickle

    from ..runner.elastic.driver import ElasticDriver
    from ..runner.launch import slot_env
    from .elastic import SparkExecDriver, SparkTaskDiscovery, \
        _elastic_task_loop

    sc = spark_context
    if sc is None:
        try:
            from pyspark import SparkContext

            sc = SparkContext._active_spark_context
        except ImportError as e:
            raise ImportError(
                "horovod_tpu.spark.run_elastic needs pyspark (or pass "
                "spark_context=)"
            ) from e
        if sc is None:
            raise ValueError("no active SparkContext")
    if num_proc is None:
        num_proc = sc.defaultParallelism
    min_np = min_np if min_np is not None else num_proc
    max_np = max_np if max_np is not None else num_proc

    payload = pickler.dumps(functools.partial(fn, *args, **(kwargs or {})))
    server = RendezvousServer()
    port = server.start()
    addr = _driver_addr()
    server.handle_put("spark_payload/fn", payload)

    env = dict(extra_env or {})
    if "JAX_PLATFORMS" not in env:
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("PALLAS_AXON_POOL_IPS", "")

    exec_driver = SparkExecDriver(server)
    run_id = uuid.uuid4().hex[:8]

    def create_worker(slot, extra):
        wenv = slot_env(slot, addr, port, dict(env), elastic=True)
        wenv.update(extra)
        wenv["HOROVOD_CYCLE_TIME"] = os.environ.get(
            "HOROVOD_CYCLE_TIME", "1")
        # SparkProcHandle is Popen-shaped (poll/wait/terminate/kill),
        # which is all ElasticDriver requires of a worker proc.
        return exec_driver.spawn(slot.hostname, wenv, run_id)

    driver = ElasticDriver(
        server, SparkTaskDiscovery(server, max_np), min_np, max_np,
        reset_limit=reset_limit,
    )

    # Launch max_np Spark tasks running the service loop, in a thread
    # (collect() blocks until shutdown).
    def mapper(index, iterator):
        yield _elastic_task_loop(index, addr, port)

    spark_err: List[BaseException] = []

    def spark_job():
        try:
            _run_spark_job(sc, max_np, mapper, barrier=False)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            spark_err.append(e)

    spark_thread = threading.Thread(target=spark_job, daemon=True)
    spark_thread.start()

    def wait_checking_spark(timeout: float):
        """driver.wait, but a Spark-side failure surfaces IMMEDIATELY
        instead of being masked behind the full elastic timeout."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while True:
            code = driver.wait(timeout=5.0)
            if code is not None:
                return code
            if spark_err and not driver.finished:
                raise spark_err[0]
            if _time.monotonic() > deadline:
                return None

    try:
        if verbose >= 1:
            logger.info(
                "spark elastic: launching %d task services "
                "(window %d..%d)", max_np, min_np, max_np)
        driver.wait_for_available_slots(min_np, timeout=start_timeout)
        driver.start(create_worker)
        code = wait_checking_spark(timeout=start_timeout * 4)
        if code is None:
            raise RuntimeError("elastic spark job timed out")
        if code != 0:
            raise RuntimeError(
                f"elastic spark job failed with exit code {code}"
            )
        results = []
        r = 0
        while True:
            blob = server.handle_get(f"spark_results/{r}")
            if blob is None:
                break
            results.append(pickle.loads(blob))
            r += 1
        if not results:
            raise RuntimeError("no ranks produced results")
        if spark_err:
            raise spark_err[0]
        return results
    finally:
        driver.stop()
        exec_driver.shutdown()
        spark_thread.join(timeout=30)
        server.stop()
