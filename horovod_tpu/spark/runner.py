"""horovod_tpu.spark.run — launch distributed training inside Spark
tasks (ref: horovod/spark/runner.py:195-301 run / :303 run_elastic).

Orchestration (mirrors the reference's shape):
  1. driver starts a rendezvous/KV server;
  2. one Spark task per rank executes `_task_fn` (barrier-stage
     semantics when available): each task registers its host, receives
     its slot assignment, sets the HOROVOD_* env, runs the user fn, and
     ships the pickled result back through the KV;
  3. results return in rank order.

The Spark interaction is confined to `_mapper` + `_run_spark_job`, so
the orchestration is testable without a cluster (tests inject a mock
SparkContext) and any pyspark ≥2.4 works at runtime.
"""
from __future__ import annotations

import os
import pickle
import socket
from typing import Any, Callable, Dict, List, Optional

from ..runner.hosts import HostInfo, get_host_assignments
from ..runner.rendezvous_server import RendezvousServer
from ..utils import env as env_cfg


def _driver_addr() -> str:
    return os.environ.get("HVDRUN_DRIVER_ADDR") or socket.gethostname()


def _task_fn(index: int, driver_addr: str, driver_port: int,
             payload: bytes, extra_env: Dict[str, str]):
    """Runs inside the Spark executor (ref: horovod/spark/task/)."""
    from ..backend.rendezvous import RendezvousClient

    client = RendezvousClient(driver_addr, driver_port, timeout=300.0)
    client.put("spark_hosts", str(index), socket.gethostname().encode())
    # Driver computes assignments once all tasks registered.
    row = client.wait_get("spark_assign", str(index)).decode()
    rank, size, lrank, lsize, crank, csize = (int(v) for v in row.split(","))
    env = {
        env_cfg.RANK: str(rank),
        env_cfg.SIZE: str(size),
        env_cfg.LOCAL_RANK: str(lrank),
        env_cfg.LOCAL_SIZE: str(lsize),
        env_cfg.CROSS_RANK: str(crank),
        env_cfg.CROSS_SIZE: str(csize),
        env_cfg.RENDEZVOUS_ADDR: driver_addr,
        env_cfg.RENDEZVOUS_PORT: str(driver_port),
        env_cfg.CONTROLLER: "tcp",
        env_cfg.CPU_OPERATIONS: "tcp",
    }
    env.update(extra_env)
    os.environ.update(env)
    fn = pickle.loads(payload)
    result = fn()
    client.put("spark_results", str(rank), pickle.dumps(result))
    return rank


def _assign_ranks(server: RendezvousServer, num_proc: int):
    """Group registered tasks by host-hash into the reference's
    rank/local/cross topology (ref: spark/runner.py:230-260 host-hash
    grouping)."""
    by_host: Dict[str, List[int]] = {}
    order: List[int] = []
    for i in range(num_proc):
        host = server.handle_get(f"spark_hosts/{i}")
        host = host.decode() if host else f"unknown-{i}"
        by_host.setdefault(host, []).append(i)
        order.append(i)
    hosts = [HostInfo(h, len(idxs)) for h, idxs in by_host.items()]
    slots = get_host_assignments(hosts, num_proc, num_proc)
    # Map slot -> task index: the k-th task on a host takes that host's
    # k-th slot.
    it = {h: list(idxs) for h, idxs in by_host.items()}
    for slot in slots:
        task_index = it[slot.hostname].pop(0)
        server.handle_put(
            f"spark_assign/{task_index}", slot.to_response_string().encode()
        )


def _run_spark_job(sc, num_proc: int, mapper):
    """Execute mapper over num_proc partitions, barrier-mode when the
    cluster supports it (ref: spark/runner.py barrier usage)."""
    rdd = sc.parallelize(range(num_proc), num_proc)
    try:
        return rdd.barrier().mapPartitionsWithIndex(mapper).collect()
    except AttributeError:  # pre-2.4 or mock without barrier
        return rdd.mapPartitionsWithIndex(mapper).collect()


def run(
    fn: Callable[[], Any],
    args=(),
    kwargs=None,
    num_proc: Optional[int] = None,
    extra_env: Optional[Dict[str, str]] = None,
    verbose: int = 1,
    spark_context=None,
) -> List[Any]:
    """Run `fn` on `num_proc` Spark tasks; per-rank results in rank order
    (ref: horovod/spark/runner.py:195 signature subset)."""
    import functools

    try:
        import cloudpickle as pickler
    except ImportError:
        pickler = pickle

    sc = spark_context
    if sc is None:
        try:
            from pyspark import SparkContext

            sc = SparkContext._active_spark_context
        except ImportError as e:
            raise ImportError(
                "horovod_tpu.spark.run needs pyspark (or pass "
                "spark_context=); for non-Spark clusters use "
                "horovod_tpu.runner.run"
            ) from e
        if sc is None:
            raise ValueError("no active SparkContext")
    if num_proc is None:
        num_proc = sc.defaultParallelism

    payload = pickler.dumps(functools.partial(fn, *args, **(kwargs or {})))
    server = RendezvousServer()
    port = server.start()
    addr = _driver_addr()
    env = dict(extra_env or {})
    # Same platform-leak guard as runner.run(): Spark task workers fork
    # from a driver that may hold a single tunneled accelerator they
    # cannot re-register; default them to CPU unless the caller opts in.
    if "JAX_PLATFORMS" not in env:
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("PALLAS_AXON_POOL_IPS", "")

    # Driver-side assignment thread: wait for all registrations, then
    # publish the topology rows.
    import threading

    def assigner():
        import time

        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            if all(
                server.handle_get(f"spark_hosts/{i}") is not None
                for i in range(num_proc)
            ):
                _assign_ranks(server, num_proc)
                return
            time.sleep(0.1)

    t = threading.Thread(target=assigner, daemon=True)
    t.start()

    def mapper(index, iterator):
        yield _task_fn(index, addr, port, payload, env)

    try:
        _run_spark_job(sc, num_proc, mapper)
        results = []
        for r in range(num_proc):
            blob = server.handle_get(f"spark_results/{r}")
            if blob is None:
                raise RuntimeError(f"rank {r} produced no result")
            results.append(pickle.loads(blob))
        return results
    finally:
        server.stop()


def run_elastic(fn, args=(), kwargs=None, num_proc=None,
                min_np=None, max_np=None, **extra):
    """Elastic variant (ref: spark/runner.py:303). Spark's task-retry
    model supplies the respawn; state handling uses hvd.elastic in the
    task fn. Currently delegates to run() with Spark-level retries —
    there is no mid-job rescale, so a min_np/max_np window is not
    honored and we say so rather than silently dropping it."""
    import inspect
    import warnings

    if (min_np is not None and min_np != num_proc) or (
        max_np is not None and max_np != num_proc
    ):
        warnings.warn(
            "horovod_tpu.spark.run_elastic runs at a fixed num_proc via "
            "Spark task retries; min_np/max_np rescaling is not "
            "supported and will be ignored",
            stacklevel=2,
        )
    # Forward everything run() itself accepts (spark_context, env, ...);
    # warn only about genuinely unsupported arguments.
    accepted = set(inspect.signature(run).parameters)
    passthrough = {k: v for k, v in extra.items() if k in accepted}
    unknown = sorted(set(extra) - accepted)
    if unknown:
        warnings.warn(
            f"run_elastic ignoring unsupported arguments: {unknown}",
            stacklevel=2,
        )
    return run(fn, args=args, kwargs=kwargs, num_proc=num_proc,
               **passthrough)
