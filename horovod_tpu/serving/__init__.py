"""Serving plane: continuous-batching inference over the mesh
(docs/serving.md).

Every rank calls :func:`serve` after ``hvd.init()``. Rank 0 opens the
HTTP front door (``HOROVOD_SERVING_PORT``), admits requests into a
bounded queue (full → 429 backpressure), coalesces them with the
event-driven continuous batcher, and drives the mesh in rounds over
the engine's collectives; every rank runs the model forward on its
slice of each batch. Weight hot-swap rides the durability plane
(checkpoint manifests + the rendezvous KV); wedged replicas are
evicted through the liveness plane's verdicts and traffic reroutes to
the survivors.

    def model_fn(weights, payloads):          # list in, list out
        return [weights["w"] * p for p in payloads]

    hvd.init()
    report = hvd.serving.serve(model_fn, weights={"w": 2.0})

Programmatic (no HTTP) use: build an `InferenceFrontend` with
``port=None``... or just call `serve(..., max_requests=N)` and drive
requests through `frontend.submit` from another thread.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..common import basics
from ..utils import env as env_cfg
from ..utils.logging import get_logger
from . import doors as doors_mod
from .autoscaler import ServingAutoscaler  # noqa: F401
from .batcher import (AdmissionQueue, ContinuousBatcher,  # noqa: F401
                      InferenceRequest)
from .doors import DoorGuard, DoorManager  # noqa: F401
from .frontend import InferenceFrontend  # noqa: F401
from .replicas import (ReplicaSet, ServingCoordinator,  # noqa: F401
                       current, failed_rank_from_error, follower_loop,
                       parked_loop)
from .weights import (BackgroundLoader, CheckpointWeightSource,  # noqa: F401
                      StaticWeightSource, WeightSource)

logger = get_logger()


def _rendezvous_from_env():
    """The launcher's rendezvous KV when configured — the same control
    plane the durability plane publishes `ckpt/latest` on and the
    liveness plane publishes verdicts on."""
    addr = env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR, "")
    port = env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0)
    if not addr or port <= 0:
        return None
    from ..backend.rendezvous import RendezvousClient

    return RendezvousClient(addr, port)


def serve(model_fn: Callable, weights=None,
          weight_source: Optional[WeightSource] = None,
          frontend: Optional[InferenceFrontend] = None,
          port: Optional[int] = None,
          tick_seconds: float = 0.25,
          max_requests: Optional[int] = None,
          registry=None) -> dict:
    """Run this rank as a serving replica until STOP; returns the
    rank's final status dict (rounds, batches, verdicts, weight step).

    SPMD: every rank of the initialized mesh must call this. `weights`
    is whatever `model_fn` understands; `weight_source` defaults to a
    `CheckpointWeightSource` over ``HOROVOD_CHECKPOINT_DIR`` when that
    is set (hot-swap on), else static weights. `max_requests` stops the
    plane after that many requests reached a terminal status (tests /
    bounded smokes); production stops via ``POST /admin/stop``."""
    from .replicas import _set_current

    if not basics.is_initialized():
        raise RuntimeError("hvd.init() must run before serving")
    rendezvous = _rendezvous_from_env()
    if weight_source is None:
        ckpt_dir = env_cfg.checkpoint_dir()
        if ckpt_dir:
            weight_source = CheckpointWeightSource(ckpt_dir)
    rs = ReplicaSet(model_fn, weights=weights,
                    weight_source=weight_source, registry=registry)
    _set_current(rs)
    # The first HOROVOD_SERVING_DOORS live ranks open front doors; the
    # lowest (communicator rank 0) is the ACTIVE one driving rounds,
    # the rest are standby doors forwarding admissions (doors.py).
    rs.doors = rs.members[:min(env_cfg.serving_doors(), len(rs.members))]
    fe = frontend
    own_frontend = False
    try:
        if rs.my_world in rs.doors:
            if fe is None:
                own_frontend = True
                fe = InferenceFrontend(
                    port=port, registry=rs.registry,
                    status_fn=rs.status).start()
            rs.guard = doors_mod.DoorGuard(
                rendezvous, epoch=0, active=(basics.rank() == 0))
            fe.door_guard = rs.guard
            rs.door_queue = fe.queue
            _register_view(rs, fe)
            _wire_alert_rules(fe)
            if max_requests is not None:
                _arm_request_cap(fe, rs, max_requests)

            def on_reinit():
                # A re-mesh re-inits the engine (new exporters, a
                # fresh AlertEngine built from defaults+env): the
                # /serving view must follow onto the new endpoint AND
                # the serving rules must be re-wired with the live
                # frontend config, or the new engine alerts against
                # the env defaults instead of the actual queue bound.
                _register_view(rs, fe)
                _wire_alert_rules(fe)

            rs.on_reinit = on_reinit
        rs._update_lease()
        # -- role loop: the same rank may be follower, then parked,
        # then follower again — or win an election and end up the
        # coordinator. Every path exits through a terminal status.
        while True:
            if basics.rank() == 0:
                rs.door = None  # the active door forwards to nobody
                doors_mod.publish_door_row(
                    rendezvous, epoch=rs.door_epoch, door=rs.my_world,
                    doors=[d for d in rs.doors if d in rs.members],
                    members=rs.members)
                from ..common import events as events_mod

                events_mod.emit(
                    events_mod.SERVING_DOOR_ELECTED, rank=rs.rank,
                    door=rs.my_world, epoch=rs.door_epoch,
                    doors=[d for d in rs.doors if d in rs.members])
                autoscaler = ServingAutoscaler(
                    rendezvous,
                    interval=env_cfg.serving_autoscale_interval_seconds(),
                    min_replicas=max(
                        len([d for d in rs.doors if d in rs.members]), 1),
                    registry=rs.registry)
                coord = ServingCoordinator(
                    rs, fe, tick_seconds=tick_seconds,
                    rendezvous=rendezvous,
                    on_remesh=rs.on_reinit,
                    autoscaler=autoscaler)
                report = coord.run()
                report["port"] = fe.port
                return report
            if rs.door is None and rs.my_world in rs.doors:
                rs.door = doors_mod.DoorManager(fe, rs.my_world)
            outcome = follower_loop(rs)
            if outcome == "stop":
                return rs.status()
            if outcome == "parked":
                if parked_loop(rs, rendezvous) == "stop":
                    return rs.status()
                continue  # re-admitted: back to a serving role
            # outcome == "promote": this rank won the election.
            rs.note_election()
            if fe is None:
                # A non-door replica can inherit the fleet when every
                # door before it died; it opens a door now.
                own_frontend = True
                fe = InferenceFrontend(
                    port=port, registry=rs.registry,
                    status_fn=rs.status).start()
                rs.guard = doors_mod.DoorGuard(
                    rendezvous, epoch=rs.door_epoch, active=True)
                fe.door_guard = rs.guard
                rs.door_queue = fe.queue
                rs.on_reinit = lambda: (_register_view(rs, fe),
                                        _wire_alert_rules(fe))
            if rs.door is not None:
                # Pending forwarded work this door admitted: head of
                # the queue (oldest admitted); half-streamed responses
                # were already error-terminated by promote().
                pending = rs.door.promote()
                if pending:
                    fe.queue.requeue_front(pending)
                rs.door = None
            rs._update_lease()
            _register_view(rs, fe)
            _wire_alert_rules(fe)
            logger.warning(
                "serving: world rank %d won the door election at epoch "
                "%d; taking over the rounds", rs.my_world, rs.door_epoch)
    finally:
        _set_current(None)
        _unregister_view()
        if own_frontend and fe is not None:
            fe.stop()


def _register_view(rs: ReplicaSet, frontend: InferenceFrontend):
    """Serve the serving status at `/serving` on the rank-0 metrics
    endpoint via the extensible view registry (no constructor kwargs
    through metrics_export). The engine's `/status` body embeds the
    same snapshot under a `serving` key (engine/engine.py)."""
    eng = basics.engine()
    if eng is None:
        return
    from ..common.metrics_export import MetricsHTTPServer

    def view():
        st = rs.status()
        st["frontend"] = frontend.basic_status()
        st["slo_p99_ms"] = env_cfg.serving_slo_p99_ms() or None
        return st

    for exp in getattr(eng, "_exporters", []):
        if isinstance(exp, MetricsHTTPServer):
            exp.add_view("serving", view)


def _wire_alert_rules(frontend: InferenceFrontend):
    """Refresh the serving-specific alert rules (docs/health.md) with
    this plane's LIVE configuration: the admission-saturation bound
    follows the frontend's actual queue capacity (a programmatic
    frontend may differ from the env default), and the p99 SLO target
    re-reads HOROVOD_SERVING_SLO_P99_MS in case it was set after
    hvd.init() armed the defaults. Parameters the user pinned via
    HOROVOD_ALERT_RULES win over both derived values."""
    eng = basics.engine()
    alerts_eng = getattr(eng, "alerts", None) if eng is not None else None
    if alerts_eng is None:
        return
    for rule in alerts_eng.rules:
        if (rule.name == "admission_queue_saturated"
                and "threshold" not in rule._overridden):
            rule.threshold = 0.9 * frontend.queue.maxsize
        elif (rule.name == "serving_p99_slo"
                and "target_s" not in rule._overridden):
            rule.target_s = env_cfg.serving_slo_p99_ms() / 1e3


def _unregister_view():
    """Detach `/serving` when serve() exits — a stale view would pin
    the dead replica set (staged weights included) for process lifetime
    and keep answering with frozen state instead of 404."""
    try:
        eng = basics.engine()
    except Exception:
        # A parked rank already shut the communicator down: nothing to
        # detach, the exporters died with it.
        return
    if eng is None:
        return
    from ..common.metrics_export import MetricsHTTPServer

    for exp in getattr(eng, "_exporters", []):
        if isinstance(exp, MetricsHTTPServer):
            exp.remove_view("serving")


def _arm_request_cap(frontend: InferenceFrontend, rs: ReplicaSet,
                     max_requests: int):
    """Stop the plane once `max_requests` requests reached a terminal
    status — a bounded-run harness for tests and smokes. Polls the
    status counters off-thread (cheap; the serving loop ticks anyway)."""
    reg = rs.registry

    def total() -> float:
        n = 0.0
        for m in reg.metrics():
            if m.name == "horovod_serving_requests_total":
                n += m.value
        return n

    base = total()

    def watch():
        while not frontend.stopping:
            if total() - base >= max_requests:
                frontend.request_stop()
                return
            time.sleep(0.05)

    threading.Thread(target=watch, name="hvd-serving-cap",
                     daemon=True).start()
