"""Weight sources: where serving replicas get (and refresh) weights.

The hot-swap protocol (docs/serving.md) rides the durability plane: a
live or restarted training job two-phase-commits checkpoint manifests
(common/checkpoint.py). The serving coordinator polls a
`WeightSource` every
``HOROVOD_SERVING_WEIGHT_REFRESH_SECONDS``; when a newer step appears
it broadcasts PREPARE (every replica loads shards in the background,
traffic uninterrupted), and once every replica reports the staged step
it broadcasts COMMIT — the flip happens between batches, so no request
is ever dropped or answered by a half-swapped replica.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..common import checkpoint as ckpt
from ..utils.logging import get_logger

logger = get_logger()


class WeightSource:
    """Interface: `poll()` names the newest available weight version
    (an int step, or None); `load(step)` materializes that version's
    weights on the calling rank. `load` runs on a background thread and
    may take arbitrarily long; `poll` runs on the coordinator's serving
    loop and must be cheap (a listdir / KV get, not a read)."""

    def poll(self) -> Optional[int]:
        raise NotImplementedError

    def load(self, step: int):
        raise NotImplementedError


class StaticWeightSource(WeightSource):
    """No refresh: serve the weights the caller handed in forever."""

    def poll(self) -> Optional[int]:
        return None

    def load(self, step: int):  # pragma: no cover - never polled
        raise RuntimeError("static weights cannot be reloaded")


class CheckpointWeightSource(WeightSource):
    """Watch a checkpoint directory (the durability plane's layout) and
    load complete manifests. `to_weights(step, objects, trees)` converts
    the reassembled checkpoint state into whatever the model_fn expects;
    the default hands back the `(objects, trees)` pair unchanged.

    The poll goes to DISK every time (a listdir + one manifest read at
    the refresh cadence — cheap). The `ckpt/latest` KV row the
    durability plane also publishes is deliberately NOT used as a
    skip-the-listdir fast path: that publish is best-effort (a commit
    whose KV put failed is still a committed checkpoint), so an
    unchanged row must never suppress discovery of a newer on-disk
    manifest — and a KV step with no complete manifest behind it is
    unloadable anyway. Disk is the truth; only disk is consulted."""

    def __init__(self, directory: str,
                 to_weights: Optional[Callable] = None):
        self.directory = directory
        self.to_weights = to_weights

    def poll(self) -> Optional[int]:
        found = ckpt.find_latest_manifest(self.directory)
        return None if found is None else found[0]

    def load(self, step: int):
        man = ckpt.load_manifest(ckpt.manifest_path(self.directory, step))
        if man is None:
            raise FileNotFoundError(
                f"checkpoint manifest for step {step} disappeared "
                f"(GC'd under the watcher?)")
        objects, trees = ckpt.load_checkpoint_arrays(self.directory, man)
        if self.to_weights is not None:
            return self.to_weights(step, objects, trees)
        return objects, trees


def publish_weights(directory: str, step: int, trees: dict,
                    objects: Optional[dict] = None,
                    rendezvous=None) -> str:
    """Publish a weight version into a checkpoint directory WITHOUT a
    training job: one complete single-shard checkpoint in the
    durability plane's exact layout (shard pickle + CRC + manifest,
    atomic renames), optionally announcing it on the KV like a real
    commit. This is the standalone-serving publish path — and what the
    serving tests/smokes use to stage a hot-swap. `trees` maps attr
    name → list of leaves, mirroring `load_checkpoint_arrays`."""
    import json
    import os
    import pickle
    import zlib

    from ..utils import atomic_file

    attrs = sorted(trees)
    leaves = [leaf for a in attrs for leaf in trees[a]]
    doc = {
        "format": ckpt.FORMAT_VERSION,
        "step": step,
        "rank": 0,
        "world_size": 1,
        "leaf_range": (0, len(leaves)),
        "leaves": leaves,
        "objects": objects or {},
        "attrs": attrs,
        "attr_counts": {a: len(trees[a]) for a in attrs},
    }
    payload = pickle.dumps(doc, protocol=pickle.HIGHEST_PROTOCOL)
    rel = ckpt.shard_file(step, 0)
    atomic_file.atomic_write_bytes(
        os.path.join(directory, rel), payload, fsync=False)
    manifest = {
        "format": ckpt.FORMAT_VERSION,
        "step": step,
        "time": time.time(),
        "world_size": 1,
        "num_leaves": len(leaves),
        "attrs": attrs,
        "attr_counts": {a: len(trees[a]) for a in attrs},
        "objects_shard": 0,
        "shards": [{"rank": 0, "file": rel, "leaves": [0, len(leaves)],
                    "bytes": len(payload),
                    "crc32": zlib.crc32(payload)}],
    }
    path = ckpt.manifest_path(directory, step)
    atomic_file.atomic_write_text(
        path, json.dumps(manifest, indent=1, sort_keys=True), fsync=False)
    if rendezvous is not None:
        try:
            rendezvous.put(ckpt.LATEST_SCOPE, ckpt.LATEST_KEY,
                           json.dumps({"step": step,
                                       "world_size": 1}).encode())
        except Exception:  # the KV row is advisory, disk is the truth
            pass
    return path


class BackgroundLoader:
    """Per-rank staged load: PREPARE starts a daemon thread loading one
    step; `staged()` names what is ready to flip. A newer PREPARE
    supersedes an in-flight load (its result is discarded on arrival if
    a newer target was set) — the coordinator only commits a step every
    rank reports staged."""

    def __init__(self, source: WeightSource):
        self.source = source
        self._lock = threading.Lock()
        self._target: Optional[int] = None
        self._staged_step: Optional[int] = None
        self._staged_weights = None
        self._error: Optional[str] = None
        self._thread: Optional[threading.Thread] = None

    def prepare(self, step: int):
        with self._lock:
            if self._target == step or self._staged_step == step:
                return  # already loading / loaded
            self._target = step
            if self._thread is not None and self._thread.is_alive():
                return  # the running loader re-checks the target when done
            self._thread = threading.Thread(
                target=self._load_loop, name="hvd-serving-loader",
                daemon=True)
            self._thread.start()

    def _load_loop(self):
        while True:
            with self._lock:
                step = self._target
                if step is None or step == self._staged_step:
                    return
            try:
                weights = self.source.load(step)
                err = None
            except Exception as e:
                weights, err = None, str(e)
                logger.warning("serving weight load for step %d failed: %s",
                               step, e)
            with self._lock:
                if err is not None:
                    self._error = err
                    if self._target == step:
                        self._target = None  # a re-poll may retry later
                        return
                    continue  # a newer target arrived; load that instead
                self._error = None
                self._staged_step = step
                self._staged_weights = weights
                if self._target == step:
                    return
                # else: a newer PREPARE landed mid-load; go again.

    def staged(self) -> Optional[int]:
        with self._lock:
            return self._staged_step

    def error(self) -> Optional[str]:
        with self._lock:
            return self._error

    def take(self, step: int):
        """Flip: hand back the staged weights for `step` (COMMIT). The
        coordinator guarantees every rank reported this step staged, so
        a miss here is a protocol bug, not a race."""
        with self._lock:
            if self._staged_step != step:
                raise RuntimeError(
                    f"commit for step {step} but staged is "
                    f"{self._staged_step}")
            w = self._staged_weights
            self._staged_weights = None
            return w

    def retry_poll(self, step: int):
        """Re-arm a failed load (poll noticed the step is still newest
        but no load is in flight)."""
        with self._lock:
            failed = self._error is not None and self._target is None
        if failed:
            self.prepare(step)
