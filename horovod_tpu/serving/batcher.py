"""Continuous batcher: bounded admission + event-driven coalescing.

The serving plane's answer to Orca-style iteration-level batching
(docs/serving.md): many small concurrent requests coalesce into one
mesh-wide dispatch. Two pieces:

* `AdmissionQueue` — the bounded front-door queue. `offer()` either
  admits a request or refuses it (the frontend turns a refusal into
  HTTP 429 backpressure); admission wakes the batcher NOW via the
  queue's condition, so an idle mesh dispatches a lone request with no
  schedule-tick latency.
* `ContinuousBatcher.next_batch()` — blocks for the first admissible
  request, then coalesces until the batch holds
  ``HOROVOD_SERVING_MAX_BATCH`` requests, the summed token budget
  reaches ``HOROVOD_SERVING_MAX_BATCH_TOKENS``, or
  ``HOROVOD_SERVING_MAX_DELAY_MS`` elapses — whichever comes FIRST.
  Like ``HOROVOD_CYCLE_TIME`` after PR 4, the delay is a max-coalescing
  bound, never a latency floor: a full batch dispatches immediately and
  new arrivals wake the wait instead of being found by polling.

Deadline-expired requests are dropped at dequeue time, BEFORE dispatch:
they are completed with status ``deadline`` (the frontend answers 504)
and counted in ``horovod_serving_requests_total{status="deadline"}``,
and never consume replica forward capacity.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import List, Optional

from ..common import telemetry

# Request terminal statuses (also the `status` label values of
# horovod_serving_requests_total, plus "rejected" which never makes a
# Request object).
STATUS_OK = "ok"
STATUS_DEADLINE = "deadline"
STATUS_ERROR = "error"
STATUS_SHUTDOWN = "shutdown"

_req_ids = itertools.count(1)


class InferenceRequest:
    """One admitted request: payload + token estimate + deadline + the
    future the HTTP handler thread parks on.

    A STREAMING request ({"stream": true}) additionally carries a
    bounded in-order frame queue: the coordinator pushes one data frame
    per serving round (``push_chunk``), the HTTP handler drains them as
    ndjson lines (``next_chunk``), and ``complete()`` — whatever path
    reaches it first: final chunk, deadline, eviction, shutdown —
    always appends a TERMINAL frame, so an interrupted stream ends with
    an error frame on the wire, never a silent hang (docs/serving.md
    "Streaming responses")."""

    __slots__ = ("id", "payload", "tokens", "enqueued", "deadline",
                 "result", "status", "error", "dispatched", "_done",
                 "stream", "n_chunks", "chunk_seq", "_frames",
                 "_frame_cond")

    def __init__(self, payload, tokens: int = 1,
                 timeout_s: float = 30.0, stream: bool = False,
                 chunks: int = 1):
        self.id = next(_req_ids)
        self.payload = payload
        self.tokens = max(int(tokens), 1)
        self.enqueued = time.monotonic()
        self.deadline = self.enqueued + max(timeout_s, 0.001)
        self.result = None
        self.status: Optional[str] = None
        self.error: Optional[str] = None
        # Set by the batcher the moment the request joins a batch: the
        # frontend's deadline handling differs for queued (504 NOW)
        # vs in-flight (grace for the reply) requests.
        self.dispatched = False
        self._done = threading.Event()
        self.stream = bool(stream)
        self.n_chunks = max(int(chunks), 1)
        # Next expected data-frame seq; push_chunk only accepts frames
        # in order, so a retransmitted round after an eviction can never
        # duplicate a chunk the client already saw.
        self.chunk_seq = 0
        self._frames: deque = deque()
        self._frame_cond = threading.Condition()

    def expired(self, now: Optional[float] = None) -> bool:
        return (now if now is not None else time.monotonic()) >= self.deadline

    def complete(self, result, status: str = STATUS_OK,
                 error: Optional[str] = None) -> bool:
        """First completion wins (a deadline drop racing a late reply
        must not flip an already-answered request). Returns whether
        THIS call settled the request — callers count terminal statuses
        only on a True return, so racing completers never double-count
        one request. For a streaming request the settling call also
        appends the terminal frame."""
        if self._done.is_set():
            return False
        self.result = result
        self.status = status
        self.error = error
        self._done.set()
        if self.stream:
            frame = {"final": True, "status": status,
                     "chunks": self.chunk_seq}
            if isinstance(result, dict) and "weight_step" in result:
                frame["weight_step"] = result["weight_step"]
            if error:
                frame["error"] = error
            with self._frame_cond:
                self._frames.append(frame)
                self._frame_cond.notify_all()
        return True

    def push_chunk(self, frame: dict) -> bool:
        """Append one data frame; in-order only (frame["seq"] must equal
        the next expected seq) and never after completion. Returns
        whether the frame was accepted — duplicates after a rerouted
        round return False and are simply dropped."""
        if not self.stream or self._done.is_set():
            return False
        if int(frame.get("seq", -1)) != self.chunk_seq:
            return False
        self.chunk_seq += 1
        with self._frame_cond:
            self._frames.append(frame)
            self._frame_cond.notify_all()
        return True

    def next_chunk(self, timeout: Optional[float] = None
                   ) -> Optional[dict]:
        """Pop the next frame (data or terminal), waiting up to
        `timeout`; None on timeout."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        with self._frame_cond:
            while not self._frames:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return None
                self._frame_cond.wait(remaining)
            return self._frames.popleft()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    @property
    def done(self) -> bool:
        return self._done.is_set()


class AdmissionQueue:
    """Bounded FIFO with wake-on-enqueue. `offer` never blocks — a full
    queue is the backpressure signal, not a parking lot."""

    def __init__(self, maxsize: int,
                 registry: Optional[telemetry.MetricsRegistry] = None):
        self.maxsize = max(int(maxsize), 1)
        self._q: deque = deque()
        self.cond = threading.Condition()
        registry = registry or telemetry.default_registry()
        self._depth_fn = self.depth
        registry.gauge(
            "horovod_serving_queue_depth",
            "Admitted inference requests waiting for dispatch",
        ).set_function(self._depth_fn)
        self._registry = registry

    def close(self):
        self._registry.gauge(
            "horovod_serving_queue_depth").clear_function(self._depth_fn)

    def depth(self) -> int:
        return len(self._q)

    def offer(self, req: InferenceRequest) -> bool:
        with self.cond:
            if len(self._q) >= self.maxsize:
                return False
            self._q.append(req)
            self.cond.notify_all()
        return True

    def requeue_front(self, reqs: List[InferenceRequest]):
        """Put a failed dispatch's requests back at the HEAD (oldest
        first), past the maxsize bound — rerouted work was already
        admitted once and must not be 429'd by its own retry."""
        with self.cond:
            for r in reversed(reqs):
                self._q.appendleft(r)
            self.cond.notify_all()

    def _pop_locked(self) -> Optional[InferenceRequest]:
        return self._q.popleft() if self._q else None

    def _peek_locked(self) -> Optional[InferenceRequest]:
        return self._q[0] if self._q else None


class ContinuousBatcher:
    """Event-driven coalescing over an AdmissionQueue (module doc)."""

    def __init__(self, queue: AdmissionQueue, max_batch: int,
                 max_tokens: int, max_delay_s: float,
                 registry: Optional[telemetry.MetricsRegistry] = None):
        self.queue = queue
        self.max_batch = max(int(max_batch), 1)
        self.max_tokens = max(int(max_tokens), 1)
        self.max_delay_s = max(float(max_delay_s), 0.0)
        registry = registry or telemetry.default_registry()
        self._m_requests = {
            status: registry.counter(
                "horovod_serving_requests_total",
                "Inference requests by terminal status",
                labels={"status": status})
            for status in (STATUS_OK, STATUS_DEADLINE, STATUS_ERROR,
                           STATUS_SHUTDOWN, "rejected")
        }
        self._m_batch_size = registry.histogram(
            "horovod_serving_batch_size",
            "Requests per dispatched batch", min_exp=0, max_exp=12)
        self._m_batch_tokens = registry.histogram(
            "horovod_serving_batch_tokens",
            "Summed request tokens per dispatched batch",
            min_exp=0, max_exp=24)
        self._m_coalesce = registry.histogram(
            "horovod_serving_coalesce_seconds",
            "Time next_batch spent coalescing after the first request")

    def count(self, status: str, n: int = 1):
        self._m_requests[status].inc(n)

    def _drop_expired_head(self, now: float) -> int:
        """Drop expired requests from the queue head (under the queue
        lock). Only the head needs checking each pass — FIFO admission
        means deadlines are (approximately) ordered; stragglers deeper
        in the queue get caught when they surface."""
        dropped = []
        while True:
            head = self.queue._peek_locked()
            if head is None or not head.expired(now):
                break
            dropped.append(self.queue._pop_locked())
        for r in dropped:
            if r.complete(None, STATUS_DEADLINE,
                          "deadline expired before dispatch"):
                self._m_requests[STATUS_DEADLINE].inc()
        return len(dropped)

    def next_batch(self, wait_timeout: float
                   ) -> Optional[List[InferenceRequest]]:
        """Return the next batch, or None after `wait_timeout` seconds
        with nothing admissible. Never returns an empty list."""
        cond = self.queue.cond
        batch: List[InferenceRequest] = []
        tokens = 0
        with cond:
            # Phase 1: wait for the first admissible request.
            arm_deadline = time.monotonic() + max(wait_timeout, 0.0)
            while True:
                now = time.monotonic()
                self._drop_expired_head(now)
                head = self.queue._peek_locked()
                if head is not None:
                    break
                remaining = arm_deadline - now
                if remaining <= 0:
                    return None
                cond.wait(remaining)
            # Phase 2: coalesce. The window opens at the first TAKE, so
            # a request that waited in the queue behind a slow dispatch
            # is not double-charged its queue dwell.
            t0 = time.monotonic()
            close = t0 + self.max_delay_s
            while True:
                now = time.monotonic()
                self._drop_expired_head(now)
                head = self.queue._peek_locked()
                if head is not None:
                    would = tokens + head.tokens
                    if batch and would > self.max_tokens:
                        break  # token budget: leave it for the next batch
                    taken = self.queue._pop_locked()
                    taken.dispatched = True
                    batch.append(taken)
                    tokens += head.tokens
                    if (len(batch) >= self.max_batch
                            or tokens >= self.max_tokens):
                        break  # size/token cap: dispatch NOW
                    continue
                if not batch:
                    # Everything we held expired mid-coalesce; re-arm.
                    remaining = arm_deadline - now
                    if remaining <= 0:
                        return None
                    cond.wait(remaining)
                    continue
                remaining = close - now
                if remaining <= 0:
                    break  # max delay: dispatch what we have
                cond.wait(remaining)
        self._m_coalesce.observe(time.monotonic() - t0)
        self._m_batch_size.observe(len(batch))
        self._m_batch_tokens.observe(tokens)
        return batch
