"""Closed-loop serving autoscaler (docs/serving.md "Serving
autoscaler").

The consumer the ``serving/load`` KV row was published for (a recorded
gap since the serving plane landed): every
``HOROVOD_SERVING_AUTOSCALE_INTERVAL_SECONDS`` the coordinator reads
the row back and decides — in the elasticity controller's shape
(runner/elastic/controller.py): a pure ``decide()``, a shared
``CooldownGate`` (cooldown = 3x the interval), decisions counted per
kind, journaled as ``serving.scale`` lifecycle events on CHANGE only,
and mirrored to the KV at ``serving``/``scale`` for operators
(scripts/hvdtop.py's serving section).

Acting is the coordinator's job (serving/replicas.py): a non-HOLD
decision turns into a ``remesh`` round — scale-down parks the highest
non-door ranks (they poll the door row and rejoin on a later
scale-up), scale-up re-admits parked ranks through the same subset
re-mesh + rendezvous machinery evictions already use. Doors are never
parked: the floor of the mesh is its door set.
"""
from __future__ import annotations

import json
import time
from typing import Optional, Tuple

from ..common import telemetry
from ..runner.elastic.controller import (CooldownGate, HOLD, SCALE_DOWN,
                                         SCALE_UP)
from ..utils.logging import get_logger
from .doors import DOOR_SCOPE, SCALE_KEY

logger = get_logger()

# Backlog watermarks, in admitted-but-unanswered requests PER REPLICA.
# Above HIGH the mesh grows (one replica per decision — conservative,
# cooldown-paced); at or below LOW it shrinks toward the door floor.
BACKLOG_HIGH = 2.0
BACKLOG_LOW = 0.25


def decide(*, backlog: float, replicas: int, min_replicas: int,
           max_replicas: int, high: float = BACKLOG_HIGH,
           low: float = BACKLOG_LOW) -> Tuple[str, int, str]:
    """Pure policy: (action, target_replicas, reason). One step per
    decision; the cooldown gate owns the pacing, this owns only the
    direction."""
    replicas = max(int(replicas), 1)
    per = float(backlog) / replicas
    if per >= high and replicas < max_replicas:
        return (SCALE_UP, replicas + 1,
                f"backlog {backlog:.0f} over {replicas} replicas "
                f"(>= {high:g}/replica)")
    if per <= low and replicas > min_replicas:
        return (SCALE_DOWN, replicas - 1,
                f"backlog {backlog:.0f} over {replicas} replicas "
                f"(<= {low:g}/replica)")
    return HOLD, replicas, "steady state"


def read_load(kv) -> Optional[dict]:
    """The ``serving/load`` row (published by the coordinator at 1 Hz:
    queue depth, inflight, replicas, weight step) — this module is its
    consumer."""
    if kv is None:
        return None
    try:
        raw = kv.get("serving", "load")
        return json.loads(raw.decode()) if raw else None
    except Exception:
        return None


class ServingAutoscaler:
    """Cadenced decide loop; the coordinator calls ``maybe()`` between
    rounds and executes any non-None plan as a remesh round."""

    def __init__(self, kv, *, interval: float, min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 registry: Optional[telemetry.MetricsRegistry] = None,
                 high: float = BACKLOG_HIGH, low: float = BACKLOG_LOW):
        self.kv = kv
        self.interval = max(float(interval), 0.0)
        self.min_replicas = max(int(min_replicas), 1)
        self.max_replicas = max_replicas
        self.high = high
        self.low = low
        self._gate = CooldownGate(self.interval * 3.0)
        self._next = 0.0
        self._last_published: Optional[tuple] = None
        registry = registry or telemetry.default_registry()
        self._m = {
            d: registry.counter(
                "horovod_serving_scale_decisions_total",
                "Serving autoscaler decisions by kind",
                labels={"decision": d})
            for d in (SCALE_UP, SCALE_DOWN, HOLD)
        }

    @property
    def enabled(self) -> bool:
        return self.interval > 0 and self.kv is not None

    def maybe(self, *, replicas: int, parked: int,
              fallback_backlog: float = 0.0,
              now: Optional[float] = None
              ) -> Optional[Tuple[str, int, str]]:
        """One cadenced observe→decide pass. Returns (action, target,
        reason) only when the mesh should actually change; None on
        hold, cooldown, off-cadence, or disabled."""
        if not self.enabled:
            return None
        now = time.monotonic() if now is None else now
        if now < self._next:
            return None
        self._next = now + self.interval
        row = read_load(self.kv)
        if row is not None:
            backlog = max(float(row.get("queue_depth", 0)),
                          float(row.get("inflight", 0)))
        else:
            backlog = float(fallback_backlog)
        # Growth is bounded by the ranks that actually exist: the
        # current mesh plus whoever is parked waiting for re-admission.
        cap = replicas + max(int(parked), 0)
        if self.max_replicas is not None:
            cap = min(cap, self.max_replicas)
        action, target, reason = decide(
            backlog=backlog, replicas=replicas,
            min_replicas=self.min_replicas, max_replicas=cap,
            high=self.high, low=self.low)
        if action != HOLD and self._gate.veto(now):
            action, target, reason = (
                HOLD, replicas,
                f"cooldown ({self._gate.cooldown:.0f}s) after the "
                "last scale")
        self._m[action].inc()
        self._publish(action, target, replicas, reason, backlog)
        if action == HOLD:
            return None
        self._gate.fired(now)
        logger.warning("serving autoscaler: %s %d -> %d (%s)",
                       action, replicas, target, reason)
        return action, target, reason

    def _publish(self, action: str, target: int, replicas: int,
                 reason: str, backlog: float):
        # Journal on CHANGE only (docs/events.md): a steady HOLD is
        # one fact, not a stream.
        if (action, target, reason) != self._last_published:
            self._last_published = (action, target, reason)
            from ..common import events as events_mod

            events_mod.emit(events_mod.SERVING_SCALE,
                            severity=(events_mod.INFO if action == HOLD
                                      else events_mod.WARN),
                            rank=-1, action=action, replicas=replicas,
                            target=target, backlog=backlog,
                            reason=reason)
        try:
            self.kv.put(DOOR_SCOPE, SCALE_KEY, json.dumps({
                "wall": time.time(), "action": action,
                "replicas": replicas, "target": target,
                "backlog": backlog, "reason": reason,
            }, separators=(",", ":")).encode())
        except Exception:  # pragma: no cover - observability only
            pass
