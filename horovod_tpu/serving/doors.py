"""Redundant front doors: leases, the election fence, and forwarding.

docs/serving.md "Redundant front doors". With
``HOROVOD_SERVING_DOORS=N`` the first N live ranks each open the HTTP
frontend. Exactly ONE — the lowest live rank, which is also
communicator rank 0 — is the ACTIVE door driving rounds; the others
are STANDBY doors that admit requests against a bounded-queue lease
and forward them through the existing round protocol:

* a standby door's round REPLY carries an ``admit`` list — the
  requests it just pulled from its local batcher (the reply is
  allgathered, so the coordinator sees it without a new channel);
* the coordinator's round COMMANDS carry ``complete``/``chunks`` maps
  keyed by request id — each id is namespaced ``"<origin world
  rank>:<local id>"``, so every door picks out its own completions
  from the broadcast and settles its local futures.

The admission budget (``HOROVOD_SERVING_QUEUE_DEPTH``) is split into
per-door leases over the rendezvous KV's door row — bounded queues,
never a global lock: admission itself costs zero KV traffic.

**Election.** The door row (``serving``/``door``) carries the
membership and an EPOCH that increments on every re-mesh. When the
active door dies, survivors re-mesh (serving/replicas.py) and the new
communicator rank 0 — the lowest live world rank — promotes itself:
publishes the row at the bumped epoch, re-registers the ``/serving``
view, and requeues its pending forwarded work at the head. Every
participant of the re-mesh bumps its epoch in lockstep; a door that
did NOT participate (drained, wedged-but-alive) keeps its old lease
epoch, and ``DoorGuard.stale()`` — checked on every admission —
rejects its late admissions with 503: the epoch fence that stops a
stale door from double-admitting against a budget it no longer holds.
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

from ..utils.logging import get_logger
from .batcher import (InferenceRequest, STATUS_DEADLINE, STATUS_ERROR,
                      STATUS_OK, STATUS_SHUTDOWN)

logger = get_logger()

# KV scope/keys of the door control rows (next to serving/load).
DOOR_SCOPE = "serving"
DOOR_KEY = "door"
SCALE_KEY = "scale"

_TERMINAL = (STATUS_OK, STATUS_DEADLINE, STATUS_ERROR, STATUS_SHUTDOWN)


def lease_slots(total_depth: int, n_doors: int) -> int:
    """One door's share of the fleet admission budget: the total queue
    depth split evenly, never below one slot (a door that cannot admit
    anything is not a door)."""
    return max(int(total_depth) // max(int(n_doors), 1), 1)


def publish_door_row(kv, *, epoch: int, door: int, doors: List[int],
                     members: List[int], stopped: bool = False):
    """Publish the door row — the single agreement point for election
    epoch, active door, door set, and mesh membership. Best-effort: a
    KV blink degrades freshness, never correctness (the round protocol
    itself is the ordering authority for participants)."""
    if kv is None:
        return
    try:
        kv.put(DOOR_SCOPE, DOOR_KEY, json.dumps({
            "epoch": int(epoch),
            "door": int(door),
            "doors": list(doors),
            "members": list(members),
            "stopped": bool(stopped),
            "wall": time.time(),
        }).encode())
    except Exception as e:  # pragma: no cover - KV down
        logger.warning("serving: door row publish failed: %s", e)


def read_door_row(kv) -> Optional[dict]:
    if kv is None:
        return None
    try:
        raw = kv.get(DOOR_SCOPE, DOOR_KEY)
        return json.loads(raw.decode()) if raw else None
    except Exception:
        return None


class DoorGuard:
    """One door's admission lease + the election epoch fence.

    ``stale()`` is consulted on every admission: it compares the lease
    epoch this door last participated in against the door row's
    current epoch (read through a rate-limited KV cache). A door whose
    epoch lost an election it did not participate in sees a newer row
    and refuses to admit — late requests get 503, not a seat in a
    budget the fleet already re-leased."""

    def __init__(self, kv, epoch: int = 0, slots: int = 1,
                 refresh_s: float = 0.5, active: bool = False):
        self.kv = kv
        self.epoch = int(epoch)
        self.slots = max(int(slots), 1)
        self.active = bool(active)  # is this process the ACTIVE door?
        self.refresh_s = max(float(refresh_s), 0.0)
        self._cached_epoch = self.epoch
        self._next_check = 0.0

    def renew(self, epoch: int, slots: Optional[int] = None,
              active: Optional[bool] = None):
        """Called after this door PARTICIPATED in a re-mesh: its lease
        moves to the new epoch (and possibly a new slot split)."""
        self.epoch = int(epoch)
        self._cached_epoch = max(self._cached_epoch, self.epoch)
        if slots is not None:
            self.slots = max(int(slots), 1)
        if active is not None:
            self.active = bool(active)

    def current_epoch(self) -> int:
        """The fleet's door epoch as last observed (KV read at most
        every `refresh_s`; no KV = own epoch, i.e. never stale)."""
        if self.kv is None:
            return self.epoch
        now = time.monotonic()
        if now >= self._next_check:
            self._next_check = now + self.refresh_s
            row = read_door_row(self.kv)
            if row is not None:
                self._cached_epoch = max(self._cached_epoch,
                                         int(row.get("epoch", 0)))
        return self._cached_epoch

    def stale(self) -> bool:
        return self.current_epoch() > self.epoch


class WorkItem:
    """One unit of coordinator work: a request admitted at SOME door.
    ``req`` is the local future when this coordinator's own door
    admitted it; None for a forwarded request, whose completion routes
    back to ``origin`` via the next command's ``complete``/``chunks``
    maps."""

    __slots__ = ("rid", "origin", "payload", "tokens", "deadline",
                 "stream", "n_chunks", "chunk_seq", "req")

    def __init__(self, rid: str, origin: int, payload, tokens: int,
                 deadline: float, stream: bool = False,
                 n_chunks: int = 1,
                 req: Optional[InferenceRequest] = None):
        self.rid = rid
        self.origin = origin
        self.payload = payload
        self.tokens = max(int(tokens), 1)
        self.deadline = deadline
        self.stream = bool(stream)
        self.n_chunks = max(int(n_chunks), 1)
        self.chunk_seq = 0
        self.req = req

    @classmethod
    def from_local(cls, req: InferenceRequest, origin: int) -> "WorkItem":
        w = cls(rid=f"{origin}:{req.id}", origin=origin,
                payload=req.payload, tokens=req.tokens,
                deadline=req.deadline, stream=req.stream,
                n_chunks=req.n_chunks, req=req)
        w.chunk_seq = req.chunk_seq
        return w

    @classmethod
    def from_admit(cls, doc: dict, now: Optional[float] = None
                   ) -> "WorkItem":
        """Rebuild a forwarded request from an `admit` wire doc. The
        deadline travels as REMAINING seconds (monotonic clocks do not
        compare across processes)."""
        now = time.monotonic() if now is None else now
        return cls(rid=str(doc["rid"]), origin=int(doc["origin"]),
                   payload=doc.get("payload"),
                   tokens=int(doc.get("tokens", 1)),
                   deadline=now + float(doc.get("timeout_rem", 0.0)),
                   stream=bool(doc.get("stream")),
                   n_chunks=int(doc.get("chunks", 1)))

    def expired(self, now: Optional[float] = None) -> bool:
        return (now if now is not None
                else time.monotonic()) >= self.deadline


def admit_doc(req: InferenceRequest, origin: int,
              now: Optional[float] = None) -> dict:
    """The wire form of one forwarded admission (a round-reply `admit`
    entry)."""
    now = time.monotonic() if now is None else now
    return {
        "rid": f"{origin}:{req.id}",
        "origin": origin,
        "payload": req.payload,
        "tokens": req.tokens,
        "timeout_rem": max(req.deadline - now, 0.001),
        "stream": req.stream,
        "chunks": req.n_chunks,
    }


class DoorManager:
    """A STANDBY door's forwarding bookkeeping, attached to the
    ReplicaSet as its per-round hook (``rs.door``):

    * ``reply_fields()`` drains the local batcher into this round's
      reply (``admit`` list) and raises the stop flag when an operator
      POSTed /admin/stop here;
    * ``on_command()`` applies the completions/chunks the coordinator
      routed to this origin;
    * ``on_recovery()`` re-forwards still-pending work after a re-mesh
      — and when the ACTIVE door is the one that died, terminates
      half-streamed responses with an error frame (the old
      coordinator's stream state died with it; an at-most-once stream
      ends loudly, it never silently hangs).

    Re-forwarding is idempotent: the coordinator dedups by rid, and
    the origin's futures are first-completion-wins."""

    def __init__(self, frontend, my_world: int):
        self.frontend = frontend
        self.my_world = int(my_world)
        self.pending: Dict[str, InferenceRequest] = {}
        self._reforward: List[str] = []

    # -- round hooks -----------------------------------------------------
    def reply_fields(self) -> dict:
        now = time.monotonic()
        admit: List[dict] = []
        # Re-forwards first (oldest admitted work travels first).
        for rid in self._reforward:
            req = self.pending.get(rid)
            if req is not None and not req.done:
                admit.append(admit_doc(req, self.my_world, now))
        self._reforward = []
        batch = self.frontend.batcher.next_batch(0.0)
        for req in batch or []:
            rid = f"{self.my_world}:{req.id}"
            self.pending[rid] = req
            admit.append(admit_doc(req, self.my_world, now))
        self._prune_done()
        return {"admit": admit,
                "stop_req": bool(self.frontend.stopping),
                # Admitted-but-unanswered here: the coordinator must
                # not stop while any door still owes a client an answer.
                "door_pending": (len(self.pending)
                                 + self.frontend.queue.depth())}

    def on_command(self, cmd: dict):
        """Settle local futures from the routed completion/chunk maps
        (other origins' entries are skipped by the rid prefix)."""
        mine = f"{self.my_world}:"
        for rid, frames in (cmd.get("chunks") or {}).items():
            req = self.pending.get(rid) if rid.startswith(mine) else None
            if req is None:
                continue
            for frame in frames:
                req.push_chunk(frame)
        for rid, doc in (cmd.get("complete") or {}).items():
            if not rid.startswith(mine):
                continue
            req = self.pending.pop(rid, None)
            if req is None:
                continue
            status = doc.get("status", STATUS_ERROR)
            if status not in _TERMINAL:
                status = STATUS_ERROR
            if status == STATUS_OK:
                settled = req.complete(
                    {"output": doc.get("output"),
                     "weight_step": doc.get("weight_step", -1),
                     **({"chunks": doc["chunks"]}
                        if "chunks" in doc else {})},
                    STATUS_OK)
            else:
                settled = req.complete(None, status,
                                       doc.get("error") or status)
            if settled:
                self.frontend.batcher.count(status)

    # -- failover --------------------------------------------------------
    def on_recovery(self, coordinator_died: bool):
        """After rs.recover(): decide each pending forwarded request's
        fate. Streams with emitted chunks survive a REPLICA loss (the
        coordinator still holds their state and re-drives the lost
        round) but not a COORDINATOR loss — those end with an error
        frame. Everything else re-forwards; the new (or same)
        coordinator dedups by rid."""
        self._reforward = []
        for rid, req in list(self.pending.items()):
            if req.done:
                del self.pending[rid]
                continue
            if coordinator_died and req.stream and req.chunk_seq > 0:
                if req.complete(None, STATUS_ERROR,
                                "stream interrupted by front-door "
                                "failover"):
                    self.frontend.batcher.count(STATUS_ERROR)
                del self.pending[rid]
                continue
            if coordinator_died or not (req.stream and req.chunk_seq > 0):
                self._reforward.append(rid)

    def promote(self) -> List[InferenceRequest]:
        """This door just WON the election. Half-streamed forwards end
        with an error frame (stream state died with the old
        coordinator); everything else returns — in admission order —
        for the new coordinator to requeue at the head of its own
        queue. The manager is spent afterwards."""
        keep: List[InferenceRequest] = []
        for rid, req in self.pending.items():
            if req.done:
                continue
            if req.stream and req.chunk_seq > 0:
                if req.complete(None, STATUS_ERROR,
                                "stream interrupted by front-door "
                                "failover"):
                    self.frontend.batcher.count(STATUS_ERROR)
                continue
            keep.append(req)
        self.pending = {}
        self._reforward = []
        return keep

    def fail_pending(self, reason: str):
        """Terminal shutdown: no coordinator will ever answer these."""
        for req in self.pending.values():
            if req.complete(None, STATUS_SHUTDOWN, reason):
                self.frontend.batcher.count(STATUS_SHUTDOWN)
        self.pending = {}
        self._reforward = []

    def _prune_done(self):
        dead = [rid for rid, req in self.pending.items() if req.done]
        for rid in dead:
            del self.pending[rid]
