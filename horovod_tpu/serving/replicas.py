"""Data-parallel replica workers + the serving round protocol.

Every rank of the mesh runs `serve()` (serving/__init__.py) after
``hvd.init()``; rank 0 additionally owns the front door and the
batcher. The mesh then advances in **rounds** — one coordinator
broadcast (the command) + one allgather (per-rank replies) — over the
same engine collectives training uses, so serving inherits the whole
substrate: the latency channel (serving payloads are small, the size
policy routes them onto the reserved latency lane ahead of any bulk
traffic), heartbeat liveness, tracing spans, telemetry.

Round commands: ``batch`` (dispatch: the items are split contiguously
over the live replicas; each rank forwards its slice through
``model_fn`` and the results ride the reply allgather back to the
front door), ``tick`` (no work — keeps replies flowing while the
queue is idle) and ``stop`` (drain + exit the loop on every rank).

Weight hot-swap **piggybacks on every round** rather than competing
with traffic for rounds (a busy mesh would otherwise starve the swap
forever — there is always a next batch):

* ``prepare: step`` on a round makes every rank start (idempotently)
  a background shard load for that checkpoint step; replies carry
  each rank's staged step, traffic continues untouched.
* once EVERY reply reports the step staged, the coordinator attaches
  ``commit: step``: each rank flips to its staged weights at the TOP
  of the round, before any forward — so the flip lands between
  batches, every item of the commit round is answered by the new
  weights on every replica, zero requests are dropped, and no
  half-swapped replica ever answers. Both verbs are idempotent, so a
  commit round lost to an eviction replays safely on the re-meshed
  survivors.

**Eviction**: a wedged replica stops heartbeating; the liveness plane
(PR 5) declares it dead and every survivor's collective raises a
root-caused error naming it. The serving loop catches that error,
records the verdict, re-meshes the SURVIVORS as a subset communicator
(`hvd.init(ranks=...)` — the rendezvous KV outlives any one rank), and
requeues the interrupted batch at the head of the admission queue — in
-flight work reroutes to the remaining replicas instead of being
dropped. If rank 0 (the front door) is the one declared dead, serving
is over: followers re-raise.
"""
from __future__ import annotations

import re
import threading
import time
from typing import Callable, List, Optional

from ..common import basics, telemetry
from ..common.exceptions import HorovodInternalError
from ..common.functions import allgather_object, broadcast_object
from ..utils import env as env_cfg
from ..utils.logging import get_logger
from .batcher import STATUS_ERROR, STATUS_OK, STATUS_SHUTDOWN
from .weights import BackgroundLoader, StaticWeightSource, WeightSource

logger = get_logger()

# Tracing categories (docs/serving.md): the serving life of a request.
CAT_SERVE = "serve"

_current_lock = threading.Lock()
_current: Optional["ReplicaSet"] = None


def current() -> Optional["ReplicaSet"]:
    """The live replica set in this process (engine /status wires this
    into the `serving` view), or None outside serve()."""
    return _current


def _set_current(rs: Optional["ReplicaSet"]):
    global _current
    with _current_lock:
        _current = rs


def slice_bounds(n: int, world: int, idx: int) -> "tuple[int, int]":
    """Contiguous batch split: replica `idx` of `world` takes items
    [n*idx/world, n*(idx+1)/world). The bounds tile [0, n) exactly for
    any world (empty slices when world > n)."""
    return n * idx // world, n * (idx + 1) // world


def failed_rank_from_error(exc: BaseException) -> Optional[int]:
    """The rank the liveness verdict names, in CURRENT-communicator
    numbering. Structured attribution (`TransportError.peer`) when the
    error surfaced locally; the verdict text ("rank 2 (host X) declared
    dead by rank 0: ...") when it arrived as a broadcast ERROR — PR 5
    guarantees the text leads with the failed rank, never the
    reporter."""
    peer = getattr(exc, "peer", None)
    if isinstance(peer, int):
        return peer
    m = re.search(r"rank (\d+)", str(exc))
    return int(m.group(1)) if m else None


class ReplicaSet:
    """One rank's view of the serving mesh: the model, the weights, the
    staged hot-swap state, and the round protocol."""

    def __init__(self, model_fn: Callable, weights=None,
                 weight_source: Optional[WeightSource] = None,
                 registry: Optional[telemetry.MetricsRegistry] = None):
        self.model_fn = model_fn
        self.weights = weights
        self.weight_source = weight_source or StaticWeightSource()
        self.loader = BackgroundLoader(self.weight_source)
        self.weight_step = -1  # committed step (-1 = the initial weights)
        # Current-communicator membership in ORIGINAL world-rank terms:
        # index i of `members` is the world rank serving as communicator
        # rank i. Eviction shrinks it; verdicts/reports always name
        # world ranks so operators aren't chasing renumbered ids.
        self.members: List[int] = list(range(basics.size()))
        self.my_world = self.members[basics.rank()]
        self.verdicts: List[str] = []
        self.rounds = 0
        self.batches = 0
        self.forwarded = 0
        self.stopped = False
        eng = basics.engine()
        if registry is not None:
            self.registry = registry
        else:
            self.registry = (eng.registry if eng is not None
                             else telemetry.default_registry())
        self._m_rounds = self.registry.counter(
            "horovod_serving_rounds_total",
            "Serving protocol rounds executed, by command",
            labels={"cmd": "all"})
        self._m_batches = self.registry.counter(
            "horovod_serving_batches_total", "Batches dispatched")
        self._m_forward_s = self.registry.histogram(
            "horovod_serving_forward_seconds",
            "Per-rank model forward latency per batch slice")
        self._m_swaps = self.registry.counter(
            "horovod_serving_weight_swaps_total",
            "Weight hot-swaps committed")
        self._m_evictions = self.registry.counter(
            "horovod_serving_evictions_total",
            "Replicas evicted after a liveness verdict")
        self._m_weight_step = self.registry.gauge(
            "horovod_serving_weight_step",
            "Checkpoint step of the committed serving weights")
        self._m_weight_step.set(self.weight_step)
        self._m_replicas = self.registry.gauge(
            "horovod_serving_replicas", "Live replicas in the serving mesh")
        self._m_replicas.set(len(self.members))

    # -- helpers ---------------------------------------------------------
    @property
    def rank(self) -> int:
        return basics.rank()

    @property
    def world(self) -> int:
        return len(self.members)

    def _tracer(self):
        eng = basics.engine()
        return eng.tracer if eng is not None else None

    def _span(self, name: str, **args):
        tr = self._tracer()
        if tr is None:
            class _Noop:
                def __enter__(self):
                    return self

                def __exit__(self, *a):
                    return False

            return _Noop()
        return tr.span(name, cat=CAT_SERVE, args=args or None)

    # -- the round -------------------------------------------------------
    def run_round(self, cmd: Optional[dict]) -> List[dict]:
        """One protocol round. Rank 0 passes the command; followers
        pass None and receive it off the broadcast. Returns every
        rank's reply (allgathered, so each rank also sees the others'
        staged steps — symmetric information keeps recovery decisions
        consistent)."""
        cmd = broadcast_object(cmd, 0, name="serve.cmd")
        kind = cmd["kind"]
        results, errors = {}, {}
        # Hot-swap verbs ride every round (module doc): commit flips
        # BEFORE this round's forward so the whole batch is answered by
        # the new weights on every replica; prepare just arms the
        # background loader. Both idempotent.
        if cmd.get("commit") is not None:
            self._commit(cmd["commit"])
        if cmd.get("prepare") is not None:
            self.loader.prepare(cmd["prepare"])
        if kind == "batch":
            mine = self._my_slice(cmd["items"], cmd.get("seq", 0))
            with self._span("serve.forward", n=len(mine)):
                t0 = time.monotonic()
                results, errors = self._forward(mine)
                self._m_forward_s.observe(time.monotonic() - t0)
            self.batches += 1
        elif kind == "stop":
            self.stopped = True
        reply = {
            "world_rank": self.my_world,
            "staged": self.loader.staged(),
            "load_error": self.loader.error(),
            "committed": self.weight_step,
            "results": results,
            "errors": errors,
        }
        self.rounds += 1
        self._m_rounds.inc()
        return allgather_object(reply, name="serve.reply")

    def _my_slice(self, items: List, seq: int) -> List:
        """Contiguous split of the batch over live replicas — replica i
        of w takes items [i*n/w, (i+1)*n/w). Every rank computes the
        same cut (the item list is replicated by the broadcast), so no
        assignment needs to travel. The assignment rotates with `seq`
        so sub-world batches spread over all replicas instead of
        pinning the same ranks (with remainder splits the FIRST slices
        are the larger ones, and a fixed mapping would starve the tail
        ranks on every small batch). `seq` rides the COMMAND, not a
        local counter: per-rank batch counters can diverge across a
        mid-round eviction (a rank that died before the forward never
        counted), and diverged rotations would drop slices — a
        request nobody forwards is a dropped request."""
        lo, hi = slice_bounds(len(items), self.world,
                              (self.rank + seq) % self.world)
        return items[lo:hi]

    def _forward(self, mine: List) -> "tuple[dict, dict]":
        results, errors = {}, {}
        if not mine:
            return results, errors
        ids = [it["id"] for it in mine]
        payloads = [it["payload"] for it in mine]
        try:
            outs = self.model_fn(self.weights, payloads)
            if len(outs) != len(payloads):
                raise ValueError(
                    f"model_fn returned {len(outs)} outputs for "
                    f"{len(payloads)} inputs")
            for rid, out in zip(ids, outs):
                results[rid] = out
        except HorovodInternalError:
            raise  # transport death is recovery's problem, not the batch's
        except Exception as e:
            # A model bug fails THIS slice's requests, not the plane.
            logger.warning("serving forward failed: %s", e)
            for rid in ids:
                errors[rid] = str(e)
        self.forwarded += len(results)
        return results, errors

    def _commit(self, step: int):
        if self.weight_step == step:
            return  # replayed commit (round lost to an eviction)
        self.weights = self.loader.take(step)
        self.weight_step = step
        self._m_weight_step.set(step)
        self._m_swaps.inc()
        from ..common import events as events_mod

        events_mod.emit(events_mod.SERVING_SWAP, rank=self.rank,
                        ckpt_step=step)
        logger.info("serving weights hot-swapped to checkpoint step %d",
                    step)

    # -- eviction / re-mesh ---------------------------------------------
    def recover(self, exc: HorovodInternalError) -> int:
        """Re-mesh the survivors after a liveness verdict. Returns the
        evicted WORLD rank; raises the original error when recovery is
        impossible (unattributed failure, front door dead, or we are
        the one declared dead)."""
        dead_idx = failed_rank_from_error(exc)
        if dead_idx is None or not (0 <= dead_idx < len(self.members)):
            raise exc
        dead_world = self.members[dead_idx]
        if dead_idx == 0:
            # The front door holds every request future; nobody can
            # take over the HTTP socket. Degradation semantics
            # (docs/serving.md): rank-0 loss ends serving.
            raise exc
        if dead_world == self.my_world:
            raise exc  # we were declared dead; do not fight the verdict
        survivors = [m for m in self.members if m != dead_world]
        verdict = str(exc)
        self.verdicts.append(verdict)
        self._m_evictions.inc()
        from ..common import events as events_mod

        events_mod.emit(events_mod.SERVING_EVICT,
                        severity=events_mod.ERROR, rank=self.rank,
                        evicted_world_rank=dead_world,
                        survivors=len(survivors))
        logger.error(
            "serving: evicting world rank %d after verdict '%s'; "
            "re-meshing %d survivors", dead_world, verdict,
            len(survivors))
        basics.shutdown()
        # Subset re-init under the launcher's still-alive rendezvous
        # KV. Every survivor derives the SAME subset from the SAME
        # verdict, so the generation-scoped rendezvous keys line up.
        basics.init(ranks=survivors)
        self.members = survivors
        self._m_replicas.set(len(self.members))
        return dead_world

    # -- introspection ---------------------------------------------------
    def status(self) -> dict:
        return {
            "role": "coordinator" if self.rank == 0 else "replica",
            "world": self.world,
            "members": list(self.members),
            "rounds": self.rounds,
            "batches": self.batches,
            "forwarded": self.forwarded,
            "weight_step": self.weight_step,
            "staged_step": self.loader.staged(),
            "load_error": self.loader.error(),
            "evictions": len(self.verdicts),
            "verdicts": list(self.verdicts),
            "stopped": self.stopped,
        }


class ServingCoordinator:
    """Rank 0's driver: pulls batches from the frontend's batcher,
    chooses each round's command, completes request futures from the
    reply gather, and runs the hot-swap + eviction protocols."""

    def __init__(self, replica_set: ReplicaSet, frontend,
                 tick_seconds: float = 0.25,
                 rendezvous=None,
                 on_remesh: Optional[Callable[[], None]] = None):
        self.rs = replica_set
        self.frontend = frontend
        self.tick = max(tick_seconds, 0.01)
        self.rendezvous = rendezvous
        self.on_remesh = on_remesh
        self.refresh_s = env_cfg.serving_weight_refresh_seconds()
        self._next_poll = 0.0
        self._next_load_pub = 0.0
        # Swap state machine, driven by the reply gather: `_swap_target`
        # is the newest published step not yet committed everywhere;
        # `_all_staged` means the LAST round's replies all reported it
        # staged (so the next round may attach commit).
        self._swap_target: Optional[int] = None
        self._all_staged = False
        # Batch rotation seed; carried in each batch command so every
        # rank (however recently re-meshed) splits identically.
        self._seq = 0

    # -- weight watch ----------------------------------------------------
    def _poll_weights(self):
        if self.refresh_s <= 0:
            return
        now = time.monotonic()
        if now < self._next_poll:
            return
        self._next_poll = now + self.refresh_s
        try:
            step = self.rs.weight_source.poll()
        except Exception as e:  # a flaky store must not kill serving
            logger.warning("serving weight poll failed: %s", e)
            return
        if step is None or step <= self.rs.weight_step:
            return
        if self._swap_target == step:
            self.rs.loader.retry_poll(step)  # re-arm a failed load
            return
        self._swap_target = step
        self._all_staged = False
        from ..common import events as events_mod

        events_mod.emit(events_mod.SERVING_SWAP_PREPARE,
                        rank=self.rs.rank, ckpt_step=step)
        logger.info("serving: new weights at checkpoint step %d; "
                    "preparing hot-swap", step)

    def _publish_load(self):
        """Load signal for the elastic driver (docs/serving.md
        "Scaling"): queue depth + replica count on the rendezvous KV,
        rate-limited to once a second. Consumers (a scale controller, a
        dashboard) read `serving/load`."""
        if self.rendezvous is None:
            return
        now = time.monotonic()
        if now < self._next_load_pub:
            return
        self._next_load_pub = now + 1.0
        try:
            import json as _json

            self.rendezvous.put("serving", "load", _json.dumps({
                "queue_depth": self.frontend.queue.depth(),
                "replicas": self.rs.world,
                "weight_step": self.rs.weight_step,
                "time": time.time(),
            }).encode())
        except Exception:  # KV down: the signal is advisory
            pass

    # -- command selection ----------------------------------------------
    def _next_command(self) -> Optional[dict]:
        """Decide this round's command: one batch of work (or a tick /
        the drain-complete stop), plus the piggybacked swap verb — a
        busy mesh must never starve the swap, and the swap must never
        delay traffic already coalesced."""
        with self.rs._span("serve.batch"):
            batch = self.frontend.batcher.next_batch(self.tick)
        if batch:
            self._dispatching = batch
            self._seq += 1
            cmd = {"kind": "batch", "seq": self._seq, "items": [
                {"id": r.id, "payload": r.payload} for r in batch]}
        else:
            self._dispatching = []
            if (self.frontend.stopping
                    and self.frontend.queue.depth() == 0):
                cmd = {"kind": "stop"}
            else:
                cmd = {"kind": "tick"}
        if self._swap_target is not None and cmd["kind"] != "stop":
            if self._all_staged:
                cmd["commit"] = self._swap_target
            else:
                cmd["prepare"] = self._swap_target
        return cmd

    def _complete_batch(self, replies: List[dict]):
        batch = self._dispatching
        if not batch:
            return
        results, errors = {}, {}
        for rep in replies:
            results.update(rep.get("results") or {})
            errors.update(rep.get("errors") or {})
        with self.rs._span("serve.reply", n=len(batch)):
            for req in batch:
                if req.id in results:
                    if req.complete({"output": results[req.id],
                                     "weight_step": self.rs.weight_step},
                                    STATUS_OK):
                        self.frontend.batcher.count(STATUS_OK)
                elif req.id in errors:
                    if req.complete(None, STATUS_ERROR, errors[req.id]):
                        self.frontend.batcher.count(STATUS_ERROR)
                else:  # a slice lost to an evicted replica mid-round
                    if req.complete(None, STATUS_ERROR,
                                    "no replica answered"):
                        self.frontend.batcher.count(STATUS_ERROR)
        self.rs._m_batches.inc()
        self._dispatching = []

    def _note_staged(self, replies: List[dict]):
        """Advance the swap state machine off the reply gather — the
        only information channel that is guaranteed consistent across
        the whole (possibly just re-meshed) communicator."""
        target = self._swap_target
        if target is None:
            return
        if all(rep.get("committed") == target for rep in replies):
            self._swap_target = None  # flipped everywhere; done
            self._all_staged = False
            return
        self._all_staged = all(rep.get("staged") == target
                               for rep in replies)

    # -- the loop --------------------------------------------------------
    def run(self) -> dict:
        self._dispatching: List = []
        while not self.rs.stopped:
            self._poll_weights()
            self._publish_load()
            cmd = self._next_command()
            try:
                replies = self.rs.run_round(cmd)
            except HorovodInternalError as e:
                self._evict_and_reroute(e)
                continue
            if cmd["kind"] == "batch":
                self._complete_batch(replies)
            self._note_staged(replies)
        return self.rs.status()

    def _evict_and_reroute(self, exc: HorovodInternalError):
        batch = getattr(self, "_dispatching", [])
        try:
            self.rs.recover(exc)
        except BaseException:
            # Recovery impossible: fail the in-flight batch loudly so
            # no HTTP handler parks until its deadline.
            for req in batch:
                if req.complete(None, STATUS_SHUTDOWN, str(exc)):
                    self.frontend.batcher.count(STATUS_SHUTDOWN)
            raise
        # Survivors re-meshed: the interrupted batch reroutes. Head of
        # the queue — it is the oldest admitted work.
        if batch:
            self.frontend.queue.requeue_front(batch)
            self._dispatching = []
        # A swap in flight re-arms conservatively: the lost round may
        # have flipped SOME survivors (broadcast landed, gather died),
        # so replies must re-prove staged/committed state on the new
        # communicator before another commit travels. prepare/commit
        # are idempotent per rank, so the replay is safe either way.
        self._all_staged = False
        if self.on_remesh is not None:
            self.on_remesh()


def follower_loop(replica_set: ReplicaSet) -> dict:
    """Every non-zero rank: execute rounds until STOP, recovering
    through evictions exactly like the coordinator (each survivor's own
    latched verdict names the same dead rank)."""
    rs = replica_set
    while not rs.stopped:
        try:
            rs.run_round(None)
        except HorovodInternalError as e:
            rs.recover(e)
    return rs.status()
