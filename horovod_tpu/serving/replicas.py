"""Data-parallel replica workers + the serving round protocol.

Every rank of the mesh runs `serve()` (serving/__init__.py) after
``hvd.init()``; rank 0 additionally owns the front door and the
batcher. The mesh then advances in **rounds** — one coordinator
broadcast (the command) + one allgather (per-rank replies) — over the
same engine collectives training uses, so serving inherits the whole
substrate: the latency channel (serving payloads are small, the size
policy routes them onto the reserved latency lane ahead of any bulk
traffic), heartbeat liveness, tracing spans, telemetry.

Round commands: ``batch`` (dispatch: the items are split contiguously
over the live replicas; each rank forwards its slice through
``model_fn`` and the results ride the reply allgather back to the
front door), ``tick`` (no work — keeps replies flowing while the
queue is idle) and ``stop`` (drain + exit the loop on every rank).

Weight hot-swap **piggybacks on every round** rather than competing
with traffic for rounds (a busy mesh would otherwise starve the swap
forever — there is always a next batch):

* ``prepare: step`` on a round makes every rank start (idempotently)
  a background shard load for that checkpoint step; replies carry
  each rank's staged step, traffic continues untouched.
* once EVERY reply reports the step staged, the coordinator attaches
  ``commit: step``: each rank flips to its staged weights at the TOP
  of the round, before any forward — so the flip lands between
  batches, every item of the commit round is answered by the new
  weights on every replica, zero requests are dropped, and no
  half-swapped replica ever answers. Both verbs are idempotent, so a
  commit round lost to an eviction replays safely on the re-meshed
  survivors.

**Eviction**: a wedged replica stops heartbeating; the liveness plane
(PR 5) declares it dead and every survivor's collective raises a
root-caused error naming it. The serving loop catches that error,
records the verdict, re-meshes the SURVIVORS as a subset communicator
(`hvd.init(ranks=...)` — the rendezvous KV outlives any one rank), and
requeues the interrupted batch at the head of the admission queue — in
-flight work reroutes to the remaining replicas instead of being
dropped. Losing the ACTIVE front door is an eviction like any other:
survivors re-mesh, the new communicator rank 0 — the lowest live world
rank — wins the election, bumps the epoch on the KV door row
(serving/doors.py), re-registers the ``/serving`` view and takes over
the rounds; surviving standby doors re-forward their pending admitted
work, so every request accepted at a surviving door still answers
(docs/serving.md "Failover").

**Scaling**: the serving autoscaler (serving/autoscaler.py) turns
``serving/load`` into ``remesh`` rounds — scale-down victims park in
``parked_loop`` polling the door row, scale-up re-admits them through
the same subset init every eviction already uses.
"""
from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..common import basics, telemetry
from ..common.exceptions import HorovodInternalError, NotInitializedError
from ..common.functions import allgather_object, broadcast_object
from ..runner.elastic.controller import SCALE_UP
from ..utils import env as env_cfg
from ..utils.logging import get_logger
from . import doors as doors_mod
from .batcher import (STATUS_DEADLINE, STATUS_ERROR, STATUS_OK,
                      STATUS_SHUTDOWN)
from .doors import WorkItem
from .weights import BackgroundLoader, StaticWeightSource, WeightSource

logger = get_logger()

# Tracing categories (docs/serving.md): the serving life of a request.
CAT_SERVE = "serve"

_current_lock = threading.Lock()
_current: Optional["ReplicaSet"] = None


def current() -> Optional["ReplicaSet"]:
    """The live replica set in this process (engine /status wires this
    into the `serving` view), or None outside serve()."""
    return _current


def _set_current(rs: Optional["ReplicaSet"]):
    global _current
    with _current_lock:
        _current = rs


def slice_bounds(n: int, world: int, idx: int) -> "tuple[int, int]":
    """Contiguous batch split: replica `idx` of `world` takes items
    [n*idx/world, n*(idx+1)/world). The bounds tile [0, n) exactly for
    any world (empty slices when world > n)."""
    return n * idx // world, n * (idx + 1) // world


def failed_rank_from_error(exc: BaseException) -> Optional[int]:
    """The rank the liveness verdict names, in CURRENT-communicator
    numbering. Structured attribution (`TransportError.peer`) when the
    error surfaced locally; the verdict text ("rank 2 (host X) declared
    dead by rank 0: ...") when it arrived as a broadcast ERROR — PR 5
    guarantees the text leads with the failed rank, never the
    reporter."""
    peer = getattr(exc, "peer", None)
    if isinstance(peer, int):
        return peer
    text = str(exc)
    # Liveness verdict: "rank 2 (host x) declared dead by rank 0: ...".
    m = re.search(r"rank (\d+)[^:]*declared dead", text)
    if m:
        return int(m.group(1))
    # Transport death finalized through the engine loses the structured
    # .peer (handles fail with the stringified status): "rank 1: recv
    # from peer 0 failed: ..." — the PEER is the dead one; the leading
    # rank is the reporter.
    m = re.search(r"peer (\d+)", text)
    if m:
        return int(m.group(1))
    m = re.search(r"rank (\d+)", text)
    return int(m.group(1)) if m else None


class ReplicaSet:
    """One rank's view of the serving mesh: the model, the weights, the
    staged hot-swap state, and the round protocol."""

    def __init__(self, model_fn: Callable, weights=None,
                 weight_source: Optional[WeightSource] = None,
                 registry: Optional[telemetry.MetricsRegistry] = None):
        self.model_fn = model_fn
        self.weights = weights
        self.weight_source = weight_source or StaticWeightSource()
        self.loader = BackgroundLoader(self.weight_source)
        self.weight_step = -1  # committed step (-1 = the initial weights)
        # Current-communicator membership in ORIGINAL world-rank terms:
        # index i of `members` is the world rank serving as communicator
        # rank i. Eviction shrinks it; verdicts/reports always name
        # world ranks so operators aren't chasing renumbered ids.
        self.members: List[int] = list(range(basics.size()))
        self.my_world = self.members[basics.rank()]
        self.verdicts: List[str] = []
        self.rounds = 0
        self.batches = 0
        self.forwarded = 0
        self.stopped = False
        # -- door state (serving/doors.py) -----------------------------
        # World ranks running an HTTP front door. The ACTIVE door is
        # always members[0]: `members` stays sorted ascending, doors
        # are never parked by the autoscaler, so the lowest live world
        # rank IS communicator rank 0 after every re-mesh.
        self.doors: List[int] = self.members[:1]
        self.door_epoch = 0
        # Ranks a scale-down parked out of the mesh; they wait in
        # parked_loop and rejoin on a later scale-up.
        self.parked: List[int] = []
        self.last_cmd: Optional[dict] = None
        # Hooks serve() attaches on door ranks: the forwarding manager
        # (standby doors only), the lease/epoch guard, and this door's
        # admission queue (re-leased on every epoch bump).
        self.door: Optional[doors_mod.DoorManager] = None
        self.guard: Optional[doors_mod.DoorGuard] = None
        self.door_queue = None
        # serve() hook re-run after every re-init (the engine is new:
        # views and alert rules must re-attach to the new exporters).
        self.on_reinit: Optional[Callable[[], None]] = None
        self._lease_total = env_cfg.serving_queue_depth()
        eng = basics.engine()
        if registry is not None:
            self.registry = registry
        else:
            self.registry = (eng.registry if eng is not None
                             else telemetry.default_registry())
        self._m_rounds = self.registry.counter(
            "horovod_serving_rounds_total",
            "Serving protocol rounds executed, by command",
            labels={"cmd": "all"})
        self._m_batches = self.registry.counter(
            "horovod_serving_batches_total", "Batches dispatched")
        self._m_forward_s = self.registry.histogram(
            "horovod_serving_forward_seconds",
            "Per-rank model forward latency per batch slice")
        self._m_swaps = self.registry.counter(
            "horovod_serving_weight_swaps_total",
            "Weight hot-swaps committed")
        self._m_evictions = self.registry.counter(
            "horovod_serving_evictions_total",
            "Replicas evicted after a liveness verdict")
        self._m_weight_step = self.registry.gauge(
            "horovod_serving_weight_step",
            "Checkpoint step of the committed serving weights")
        self._m_weight_step.set(self.weight_step)
        self._m_replicas = self.registry.gauge(
            "horovod_serving_replicas", "Live replicas in the serving mesh")
        self._m_replicas.set(len(self.members))
        self._m_doors = self.registry.gauge(
            "horovod_serving_doors",
            "Live HTTP front doors in the serving fleet")
        self._m_doors.set(len(self.doors))
        self._m_elections = self.registry.counter(
            "horovod_serving_door_elections_total",
            "Door failover elections won by this process")

    # -- helpers ---------------------------------------------------------
    @property
    def rank(self) -> int:
        try:
            return basics.rank()
        except NotInitializedError:
            # A parked rank shut its communicator down; status() must
            # still answer (the stop path returns it as the report).
            return -1

    @property
    def world(self) -> int:
        return len(self.members)

    def _tracer(self):
        eng = basics.engine()
        return eng.tracer if eng is not None else None

    def _span(self, name: str, **args):
        tr = self._tracer()
        if tr is None:
            class _Noop:
                def __enter__(self):
                    return self

                def __exit__(self, *a):
                    return False

            return _Noop()
        return tr.span(name, cat=CAT_SERVE, args=args or None)

    # -- the round -------------------------------------------------------
    def run_round(self, cmd: Optional[dict]) -> List[dict]:
        """One protocol round. Rank 0 passes the command; followers
        pass None and receive it off the broadcast. Returns every
        rank's reply (allgathered, so each rank also sees the others'
        staged steps — symmetric information keeps recovery decisions
        consistent)."""
        cmd = broadcast_object(cmd, 0, name="serve.cmd")
        self.last_cmd = cmd
        kind = cmd["kind"]
        results, errors = {}, {}
        # Forwarded-work routing (serving/doors.py): a standby door
        # settles the completions/chunks the coordinator addressed to
        # it FIRST — a terminal answer must never wait on this round's
        # forward.
        if self.door is not None:
            self.door.on_command(cmd)
        # Hot-swap verbs ride every round (module doc): commit flips
        # BEFORE this round's forward so the whole batch is answered by
        # the new weights on every replica; prepare just arms the
        # background loader. Both idempotent.
        if cmd.get("commit") is not None:
            self._commit(cmd["commit"])
        if cmd.get("prepare") is not None:
            self.loader.prepare(cmd["prepare"])
        if kind == "batch":
            mine = self._my_slice(cmd["items"], cmd.get("seq", 0))
            with self._span("serve.forward", n=len(mine)):
                t0 = time.monotonic()
                results, errors = self._forward(mine)
                self._m_forward_s.observe(time.monotonic() - t0)
            self.batches += 1
        elif kind == "stop":
            self.stopped = True
            if self.door is not None:
                # The routed completions above were the last; nothing
                # will ever answer what is still pending here.
                self.door.fail_pending("serving stopped")
        # A "remesh" round carries no work: the membership change it
        # announces happens AFTER the reply gather (the caller acts on
        # rs.last_cmd), so the round itself stays a plain barrier.
        reply = {
            "world_rank": self.my_world,
            "staged": self.loader.staged(),
            "load_error": self.loader.error(),
            "committed": self.weight_step,
            "results": results,
            "errors": errors,
        }
        if self.door is not None and kind != "stop":
            reply.update(self.door.reply_fields())
        self.rounds += 1
        self._m_rounds.inc()
        return allgather_object(reply, name="serve.reply")

    def _my_slice(self, items: List, seq: int) -> List:
        """Contiguous split of the batch over live replicas — replica i
        of w takes items [i*n/w, (i+1)*n/w). Every rank computes the
        same cut (the item list is replicated by the broadcast), so no
        assignment needs to travel. The assignment rotates with `seq`
        so sub-world batches spread over all replicas instead of
        pinning the same ranks (with remainder splits the FIRST slices
        are the larger ones, and a fixed mapping would starve the tail
        ranks on every small batch). `seq` rides the COMMAND, not a
        local counter: per-rank batch counters can diverge across a
        mid-round eviction (a rank that died before the forward never
        counted), and diverged rotations would drop slices — a
        request nobody forwards is a dropped request."""
        lo, hi = slice_bounds(len(items), self.world,
                              (self.rank + seq) % self.world)
        return items[lo:hi]

    def _forward(self, mine: List) -> "tuple[dict, dict]":
        results, errors = {}, {}
        if not mine:
            return results, errors
        ids = [it["id"] for it in mine]
        payloads = [it["payload"] for it in mine]
        try:
            outs = self.model_fn(self.weights, payloads)
            if len(outs) != len(payloads):
                raise ValueError(
                    f"model_fn returned {len(outs)} outputs for "
                    f"{len(payloads)} inputs")
            for rid, out in zip(ids, outs):
                results[rid] = out
        except HorovodInternalError:
            raise  # transport death is recovery's problem, not the batch's
        except Exception as e:
            # A model bug fails THIS slice's requests, not the plane.
            logger.warning("serving forward failed: %s", e)
            for rid in ids:
                errors[rid] = str(e)
        self.forwarded += len(results)
        return results, errors

    def _commit(self, step: int):
        if self.weight_step == step:
            return  # replayed commit (round lost to an eviction)
        self.weights = self.loader.take(step)
        self.weight_step = step
        self._m_weight_step.set(step)
        self._m_swaps.inc()
        from ..common import events as events_mod

        events_mod.emit(events_mod.SERVING_SWAP, rank=self.rank,
                        ckpt_step=step)
        logger.info("serving weights hot-swapped to checkpoint step %d",
                    step)

    # -- eviction / re-mesh ---------------------------------------------
    def recover(self, exc: HorovodInternalError) -> "tuple[int, bool]":
        """Re-mesh the survivors after a liveness verdict. Returns
        ``(evicted world rank, coordinator_died)``; raises the original
        error when recovery is impossible (unattributed failure, nobody
        left, or we are the one declared dead). Losing the ACTIVE front
        door no longer ends serving (docs/serving.md "Failover"): the
        survivors re-mesh exactly as for any replica, and the new
        communicator rank 0 — the lowest live world rank — wins the
        election the epoch bump below fences."""
        dead_idx = failed_rank_from_error(exc)
        if dead_idx is None or not (0 <= dead_idx < len(self.members)):
            raise exc
        dead_world = self.members[dead_idx]
        coordinator_died = dead_idx == 0
        if dead_world == self.my_world:
            raise exc  # we were declared dead; do not fight the verdict
        survivors = [m for m in self.members if m != dead_world]
        if not survivors:
            raise exc
        verdict = str(exc)
        self.verdicts.append(verdict)
        self._m_evictions.inc()
        from ..common import events as events_mod

        events_mod.emit(events_mod.SERVING_EVICT,
                        severity=events_mod.ERROR, rank=self.rank,
                        evicted_world_rank=dead_world,
                        survivors=len(survivors))
        logger.error(
            "serving: evicting world rank %d after verdict '%s'; "
            "re-meshing %d survivors", dead_world, verdict,
            len(survivors))
        # Election bookkeeping BEFORE the re-init: the dead rank leaves
        # the door set, the survivors' head joins it (a fleet must
        # always have its active door), and the epoch bumps — any door
        # that did NOT participate in this re-mesh keeps its old lease
        # and goes stale (doors.DoorGuard).
        self.doors = [d for d in self.doors if d != dead_world]
        if survivors[0] not in self.doors:
            self.doors.append(survivors[0])
            self.doors.sort()
        self._remesh(survivors, self.door_epoch + 1)
        return dead_world, coordinator_died

    def remesh(self, members: List[int], epoch: int):
        """Autoscaler-driven membership change (a ``remesh`` round):
        every participant re-inits the subset communicator at the new
        epoch. Ascending order is the election invariant — the active
        door must come out as communicator rank 0."""
        self._remesh(sorted(int(m) for m in members), int(epoch))

    def _remesh(self, members: List[int], epoch: int):
        basics.shutdown()
        # Subset re-init under the launcher's still-alive rendezvous
        # KV. Every participant derives the SAME subset from the SAME
        # verdict/command, so the generation-scoped rendezvous keys
        # line up.
        basics.init(ranks=members)
        self.members = members
        self.door_epoch = epoch
        self._m_replicas.set(len(self.members))
        self._update_lease()
        if self.on_reinit is not None:
            try:
                self.on_reinit()
            except Exception as e:  # observability must not kill rounds
                logger.warning("serving: on_reinit hook failed: %s", e)

    def _update_lease(self):
        """Re-derive this rank's admission lease from the deterministic
        split of the fleet budget over the live doors — every
        participant computes the same split from the same membership,
        so admission itself costs zero KV traffic."""
        live_doors = [d for d in self.doors if d in self.members]
        self._m_doors.set(len(live_doors))
        slots = doors_mod.lease_slots(self._lease_total,
                                      len(live_doors) or 1)
        if self.guard is not None:
            self.guard.renew(
                self.door_epoch, slots=slots,
                active=bool(self.members
                            and self.members[0] == self.my_world))
        if self.door_queue is not None:
            self.door_queue.maxsize = max(slots, 1)

    def note_election(self):
        self._m_elections.inc()

    # -- introspection ---------------------------------------------------
    def status(self) -> dict:
        return {
            "role": "coordinator" if self.rank == 0 else "replica",
            "world": self.world,
            "members": list(self.members),
            "door": self.members[0] if self.members else -1,
            "doors": [d for d in self.doors if d in self.members],
            "door_epoch": self.door_epoch,
            "is_door": self.my_world in self.doors,
            "parked": list(self.parked),
            "rounds": self.rounds,
            "batches": self.batches,
            "forwarded": self.forwarded,
            "weight_step": self.weight_step,
            "staged_step": self.loader.staged(),
            "load_error": self.loader.error(),
            "evictions": len(self.verdicts),
            "verdicts": list(self.verdicts),
            "stopped": self.stopped,
        }


class ServingCoordinator:
    """The ACTIVE door's driver: pulls work from its own batcher AND
    from the standby doors' forwarded admissions, chooses each round's
    command, completes request futures (local) or routes completions
    back to their origin door (forwarded), and runs the hot-swap,
    eviction and autoscale protocols."""

    def __init__(self, replica_set: ReplicaSet, frontend,
                 tick_seconds: float = 0.25,
                 rendezvous=None,
                 on_remesh: Optional[Callable[[], None]] = None,
                 autoscaler=None):
        self.rs = replica_set
        self.frontend = frontend
        self.tick = max(tick_seconds, 0.01)
        self.rendezvous = rendezvous
        self.on_remesh = on_remesh
        self.autoscaler = autoscaler
        self.refresh_s = env_cfg.serving_weight_refresh_seconds()
        self._next_poll = 0.0
        self._next_load_pub = 0.0
        # Swap state machine, driven by the reply gather: `_swap_target`
        # is the newest published step not yet committed everywhere;
        # `_all_staged` means the LAST round's replies all reported it
        # staged (so the next round may attach commit).
        self._swap_target: Optional[int] = None
        self._all_staged = False
        # Batch rotation seed; carried in each batch command so every
        # rank (however recently re-meshed) splits identically.
        self._seq = 0
        # Forwarded-work state (docs/serving.md "Redundant front
        # doors"): the in-flight round's WorkItems, forwarded
        # admissions not yet dispatched, stream continuations awaiting
        # their next chunk, and the outbox of completions/chunks the
        # next command routes back to origin doors. `_remote_live`
        # dedups re-forwards by rid.
        self._dispatching: List[WorkItem] = []
        self._remote_q: "deque[WorkItem]" = deque()
        self._continuations: "deque[WorkItem]" = deque()
        self._remote_live: set = set()
        self._out_complete: Dict[str, dict] = {}
        self._out_chunks: Dict[str, List[dict]] = {}
        # Sum of the doors' admitted-but-unanswered counts, off the
        # last reply gather: the stop gate.
        self._door_pending = 0

    # -- weight watch ----------------------------------------------------
    def _poll_weights(self):
        if self.refresh_s <= 0:
            return
        now = time.monotonic()
        if now < self._next_poll:
            return
        self._next_poll = now + self.refresh_s
        try:
            step = self.rs.weight_source.poll()
        except Exception as e:  # a flaky store must not kill serving
            logger.warning("serving weight poll failed: %s", e)
            return
        if step is None or step <= self.rs.weight_step:
            return
        if self._swap_target == step:
            self.rs.loader.retry_poll(step)  # re-arm a failed load
            return
        self._swap_target = step
        self._all_staged = False
        from ..common import events as events_mod

        events_mod.emit(events_mod.SERVING_SWAP_PREPARE,
                        rank=self.rs.rank, ckpt_step=step)
        logger.info("serving: new weights at checkpoint step %d; "
                    "preparing hot-swap", step)

    def _publish_load(self):
        """Load signal on the rendezvous KV (docs/serving.md
        "Scaling"): queue depth, fleet-wide in-flight work, replica and
        door counts, rate-limited to once a second. The serving
        autoscaler (serving/autoscaler.py) is the closed-loop consumer;
        hvdtop reads it too."""
        if self.rendezvous is None:
            return
        now = time.monotonic()
        if now < self._next_load_pub:
            return
        self._next_load_pub = now + 1.0
        try:
            import json as _json

            self.rendezvous.put("serving", "load", _json.dumps({
                "queue_depth": self.frontend.queue.depth(),
                # The sum below is sampled between rounds, where the
                # queue and dispatch set are transiently empty even
                # under sustained traffic; the frontend's open-request
                # count is the quiescence-proof floor (an admitted
                # request stays open until its response is delivered).
                "inflight": max(len(self._dispatching)
                                + len(self._remote_q)
                                + len(self._continuations)
                                + self.frontend.queue.depth(),
                                self.frontend._inflight_count()),
                "replicas": self.rs.world,
                "doors": len([d for d in self.rs.doors
                              if d in self.rs.members]),
                "weight_step": self.rs.weight_step,
                "time": time.time(),
            }).encode())
        except Exception:  # KV down: the signal is advisory
            pass

    # -- command selection ----------------------------------------------
    def _next_command(self) -> Optional[dict]:
        """Decide this round's command: one batch of work (or a tick /
        the drain-complete stop), plus the piggybacked swap verb — a
        busy mesh must never starve the swap, and the swap must never
        delay traffic already coalesced. Stream continuations and
        forwarded admissions dispatch FIRST (they are the oldest
        admitted work); the local batcher tops the batch up."""
        items: List[WorkItem] = []
        cap = self.frontend.batcher.max_batch
        while self._continuations and len(items) < cap:
            items.append(self._continuations.popleft())
        now = time.monotonic()
        while self._remote_q and len(items) < cap:
            w = self._remote_q.popleft()
            if w.expired(now):
                self._finish(w, STATUS_DEADLINE,
                             error="deadline expired before dispatch")
                continue
            items.append(w)
        with self.rs._span("serve.batch"):
            batch = self.frontend.batcher.next_batch(
                0.0 if items else self.tick)
        for req in batch or []:
            items.append(WorkItem.from_local(req, self.rs.my_world))
        if items:
            self._dispatching = items
            self._seq += 1
            cmd = {"kind": "batch", "seq": self._seq, "items": [
                {"id": w.rid, "payload": w.payload} for w in items]}
        else:
            self._dispatching = []
            if (self.frontend.stopping
                    and self.frontend.queue.depth() == 0
                    and self._door_pending == 0):
                cmd = {"kind": "stop"}
            else:
                cmd = {"kind": "tick"}
        if self._swap_target is not None and cmd["kind"] != "stop":
            if self._all_staged:
                cmd["commit"] = self._swap_target
            else:
                cmd["prepare"] = self._swap_target
        self._attach_outbox(cmd)
        return cmd

    # -- completion routing ----------------------------------------------
    def _attach_outbox(self, cmd: dict):
        """Routed completions/chunks ride EVERY command — including the
        stop round, whose routed answers are the last to travel."""
        if self._out_complete:
            cmd["complete"] = self._out_complete
            self._out_complete = {}
        if self._out_chunks:
            cmd["chunks"] = self._out_chunks
            self._out_chunks = {}

    def _restore_outbox(self, cmd: Optional[dict]):
        """A round died before its gather proved delivery: put its
        routed maps back so the next command re-carries them. Safe if
        the broadcast DID land — origin futures are first-completion-
        wins and push_chunk dedups by sequence number."""
        if not cmd:
            return
        for rid, doc in (cmd.get("complete") or {}).items():
            self._out_complete.setdefault(rid, doc)
        for rid, frames in (cmd.get("chunks") or {}).items():
            self._out_chunks[rid] = frames + self._out_chunks.get(rid, [])

    def _finish(self, w: WorkItem, status: str, *, output=None,
                error: Optional[str] = None):
        """Terminal answer for one WorkItem: a local future settles
        (and counts) here; a forwarded one goes to the outbox for its
        origin door to settle and count."""
        self._remote_live.discard(w.rid)
        if w.req is not None:
            if status == STATUS_OK:
                doc = {"output": output,
                       "weight_step": self.rs.weight_step}
                if w.stream:
                    doc["chunks"] = w.req.chunk_seq
                settled = w.req.complete(doc, STATUS_OK)
            else:
                settled = w.req.complete(None, status, error or status)
            if settled:
                self.frontend.batcher.count(status)
            return
        doc = {"status": status, "weight_step": self.rs.weight_step}
        if status == STATUS_OK:
            doc["output"] = output
            if w.stream:
                doc["chunks"] = w.chunk_seq
        else:
            doc["error"] = error or status
        self._out_complete[w.rid] = doc

    def _emit_chunk(self, w: WorkItem, output):
        """One stream chunk: straight onto the local future, or into
        the outbox for the origin door. Every frame carries the step of
        the weights that produced it (docs/serving.md "Streaming")."""
        frame = {"seq": w.chunk_seq, "output": output,
                 "weight_step": self.rs.weight_step}
        if w.req is not None:
            w.req.push_chunk(frame)
            w.chunk_seq = w.req.chunk_seq
        else:
            self._out_chunks.setdefault(w.rid, []).append(frame)
            w.chunk_seq += 1

    def _complete_batch(self, replies: List[dict]):
        batch = self._dispatching
        self._dispatching = []
        if not batch:
            return
        results, errors = {}, {}
        for rep in replies:
            results.update(rep.get("results") or {})
            errors.update(rep.get("errors") or {})
        with self.rs._span("serve.reply", n=len(batch)):
            for w in batch:
                if w.rid in results:
                    if w.stream:
                        # One round == one chunk; the item re-enters
                        # the dispatch queue until its chunk budget is
                        # spent, then completes with a terminal frame.
                        self._emit_chunk(w, results[w.rid])
                        if w.chunk_seq >= w.n_chunks:
                            self._finish(w, STATUS_OK,
                                         output=results[w.rid])
                        else:
                            self._continuations.append(w)
                    else:
                        self._finish(w, STATUS_OK,
                                     output=results[w.rid])
                elif w.rid in errors:
                    self._finish(w, STATUS_ERROR, error=errors[w.rid])
                else:  # a slice lost to an evicted replica mid-round
                    self._finish(w, STATUS_ERROR,
                                 error="no replica answered")
        self.rs._m_batches.inc()

    def _ingest_replies(self, replies: List[dict]):
        """Forwarded admissions + fleet stop intent, off the reply
        gather. Re-forwards of work already in flight dedup by rid."""
        now = time.monotonic()
        pending = 0
        for rep in replies:
            pending += int(rep.get("door_pending", 0))
            if rep.get("stop_req"):
                self.frontend.request_stop()
            for doc in rep.get("admit") or []:
                rid = str(doc.get("rid"))
                if rid in self._remote_live:
                    continue
                w = WorkItem.from_admit(doc, now)
                if w.expired(now):
                    self._finish(w, STATUS_DEADLINE,
                                 error="deadline expired in transit")
                    continue
                self._remote_live.add(rid)
                self._remote_q.append(w)
        self._door_pending = pending

    def _note_staged(self, replies: List[dict]):
        """Advance the swap state machine off the reply gather — the
        only information channel that is guaranteed consistent across
        the whole (possibly just re-meshed) communicator."""
        target = self._swap_target
        if target is None:
            return
        if all(rep.get("committed") == target for rep in replies):
            self._swap_target = None  # flipped everywhere; done
            self._all_staged = False
            return
        self._all_staged = all(rep.get("staged") == target
                               for rep in replies)

    # -- autoscale -------------------------------------------------------
    def _maybe_autoscale(self) -> bool:
        """One autoscaler pass between rounds; returns True when a
        remesh round ran (the main loop restarts its cycle). Victims
        are the highest non-door ranks — doors are never parked, so
        `members` keeps its ascending-head-is-the-active-door
        invariant; scale-up re-admits the lowest parked ranks."""
        au = self.autoscaler
        if au is None or not au.enabled:
            return False
        # The floor follows the LIVE door set — a failover that shrank
        # the doors must not leave the fleet unable to shrink with it.
        au.min_replicas = max(
            len([d for d in self.rs.doors if d in self.rs.members]), 1)
        plan = au.maybe(replicas=self.rs.world,
                        parked=len(self.rs.parked),
                        fallback_backlog=self.frontend.queue.depth())
        if plan is None:
            return False
        action, target, _reason = plan
        members = list(self.rs.members)
        if action == SCALE_UP:
            add = sorted(self.rs.parked)[:max(target - len(members), 0)]
            if not add:
                return False
            new_members = sorted(members + add)
            new_parked = [p for p in self.rs.parked if p not in add]
        else:
            victims = [m for m in sorted(members, reverse=True)
                       if m not in self.rs.doors][
                           :max(len(members) - target, 0)]
            if not victims:
                return False
            new_members = [m for m in members if m not in victims]
            new_parked = sorted(self.rs.parked + victims)
        epoch = self.rs.door_epoch + 1
        cmd = {"kind": "remesh", "members": new_members, "epoch": epoch}
        self._attach_outbox(cmd)
        try:
            self.rs.run_round(cmd)
        except HorovodInternalError as e:
            self._restore_outbox(cmd)
            self._evict_and_reroute(e)
            return True
        # Lease forward BEFORE the row goes out: the row at the bumped
        # epoch is what makes every door's old lease look stale, and
        # this door keeps admitting while the re-init below runs.
        if self.rs.guard is not None:
            self.rs.guard.renew(epoch)
        # Row BEFORE the re-init: on a scale-up the parked ranks poll
        # it and must arrive at the subset init with the same
        # membership the participants re-init with — the init is the
        # barrier, the row is the invitation.
        doors_mod.publish_door_row(
            self.rendezvous, epoch=epoch, door=self.rs.my_world,
            doors=[d for d in self.rs.doors if d in new_members],
            members=new_members)
        self.rs.parked = new_parked
        self.rs.remesh(new_members, epoch)
        if self.on_remesh is not None:
            self.on_remesh()
        return True

    # -- the loop --------------------------------------------------------
    def run(self) -> dict:
        while not self.rs.stopped:
            self._poll_weights()
            self._publish_load()
            if self._maybe_autoscale():
                continue
            cmd = self._next_command()
            try:
                replies = self.rs.run_round(cmd)
            except HorovodInternalError as e:
                self._restore_outbox(cmd)
                self._evict_and_reroute(e)
                continue
            if cmd["kind"] == "batch":
                self._complete_batch(replies)
            self._ingest_replies(replies)
            self._note_staged(replies)
        # Parked ranks poll the door row; the stopped flag is their
        # exit (parked_loop).
        doors_mod.publish_door_row(
            self.rendezvous, epoch=self.rs.door_epoch + 1,
            door=self.rs.my_world,
            doors=[d for d in self.rs.doors if d in self.rs.members],
            members=self.rs.members, stopped=True)
        return self.rs.status()

    def _evict_and_reroute(self, exc: HorovodInternalError):
        batch = self._dispatching
        self._dispatching = []
        try:
            self.rs.recover(exc)
        except BaseException:
            # Recovery impossible: fail the in-flight work loudly so no
            # HTTP handler parks until its deadline. Forwarded items
            # have no route left — their origin doors settle them on
            # their own recovery path.
            for w in batch:
                if w.req is not None:
                    if w.req.complete(None, STATUS_SHUTDOWN, str(exc)):
                        self.frontend.batcher.count(STATUS_SHUTDOWN)
            raise
        # Survivors re-meshed; we are still the active door (a
        # coordinator that died would not be running this line), so
        # re-publish the row at the bumped epoch: the election fence
        # that makes any non-participant door's lease stale.
        doors_mod.publish_door_row(
            self.rendezvous, epoch=self.rs.door_epoch,
            door=self.rs.my_world,
            doors=[d for d in self.rs.doors if d in self.rs.members],
            members=self.rs.members)
        # The interrupted work reroutes. Fresh local requests go back
        # at the HEAD of the queue (oldest admitted work); items with
        # emitted chunks re-enter the continuation queue — the failed
        # round's chunk was never delivered, so the replay cannot
        # duplicate a frame — and forwarded items re-enter dispatch
        # directly.
        requeue: List = []
        for w in reversed(batch):
            if w.req is not None and w.chunk_seq == 0:
                requeue.append(w.req)
            elif w.chunk_seq > 0:
                self._continuations.appendleft(w)
            else:
                self._remote_q.appendleft(w)
        if requeue:
            self.frontend.queue.requeue_front(list(reversed(requeue)))
        # A swap in flight re-arms conservatively: the lost round may
        # have flipped SOME survivors (broadcast landed, gather died),
        # so replies must re-prove staged/committed state on the new
        # communicator before another commit travels. prepare/commit
        # are idempotent per rank, so the replay is safe either way.
        self._all_staged = False
        if self.on_remesh is not None:
            self.on_remesh()


def follower_loop(replica_set: ReplicaSet) -> str:
    """Every non-zero rank: execute rounds until one of three exits —
    ``"stop"`` (drain complete), ``"promote"`` (this rank just won a
    door election: the caller must take over the rounds), or
    ``"parked"`` (a scale-down remesh excluded this rank: the caller
    waits in parked_loop). Evictions recover in lockstep with the
    coordinator — each survivor's own latched verdict names the same
    dead rank, so every participant bumps the same epoch."""
    rs = replica_set
    while not rs.stopped:
        try:
            rs.run_round(None)
        except HorovodInternalError as e:
            _dead, coordinator_died = rs.recover(e)
            if rs.door is not None:
                rs.door.on_recovery(coordinator_died)
            if rs.rank == 0:
                return "promote"
            continue
        cmd = rs.last_cmd or {}
        if cmd.get("kind") == "remesh":
            members = [int(m) for m in cmd.get("members") or []]
            gone = [m for m in rs.members if m not in members]
            rs.parked = sorted({*rs.parked, *gone} - set(members))
            if rs.my_world not in members:
                basics.shutdown()
                return "parked"
            epoch = int(cmd.get("epoch", rs.door_epoch + 1))
            if rs.guard is not None:
                # Renew the lease the moment the cmd names this rank a
                # participant: the coordinator publishes the bumped row
                # before the re-init barrier, and a door must not
                # answer 503-stale for the whole init window.
                rs.guard.renew(epoch)
            rs.remesh(members, epoch)
    return "stop"


def parked_loop(rs: ReplicaSet, kv, poll_s: float = 0.2) -> str:
    """A scale-down victim's wait: poll the door row until a scale-up
    re-admits this rank (``"rejoin"`` — the caller resumes its serving
    role) or the fleet stops (``"stop"``). The rejoin is the same
    subset init every re-mesh uses; the row carries the membership, so
    the parked rank arrives at the collective with the same view as
    the participants already blocking in it."""
    while True:
        row = doors_mod.read_door_row(kv)
        if row is not None:
            if row.get("stopped"):
                rs.stopped = True
                return "stop"
            members = sorted(int(m) for m in row.get("members") or [])
            epoch = int(row.get("epoch", 0))
            if rs.my_world in members and epoch > rs.door_epoch:
                basics.init(ranks=members)
                rs.members = members
                rs.door_epoch = epoch
                rs.doors = sorted(int(d) for d in row.get("doors")
                                  or rs.doors)
                rs.parked = [p for p in rs.parked if p not in members]
                rs._m_replicas.set(len(rs.members))
                rs._update_lease()
                if rs.on_reinit is not None:
                    try:
                        rs.on_reinit()
                    except Exception:
                        pass
                return "rejoin"
        time.sleep(poll_s)
