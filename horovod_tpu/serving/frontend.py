"""HTTP front door for the serving plane (rank 0 / standalone).

Reuses the `metrics_export` server plumbing — the same daemon-thread
``ThreadingHTTPServer`` shape, one handler thread per in-flight client
connection — so the front door costs nothing new architecturally. The
endpoint is unauthenticated and binds loopback by default
(``HOROVOD_SERVING_ADDR``), the `HOROVOD_METRICS_ADDR` posture.

Routes:

* ``POST /v1/infer`` — body ``{"inputs": <json>, "tokens": <int>?,
  "timeout_s": <float>?}`` (or any bare JSON document, taken as the
  inputs). Admission: a full queue answers **429** with ``Retry-After``
  (backpressure — the queue bound is ``HOROVOD_SERVING_QUEUE_DEPTH``);
  an admitted request parks the handler thread on the request future
  and answers **200** ``{"output": ..., "weight_step": ...}``, **504**
  when the per-request deadline expired (before OR after dispatch), or
  **500**/**503** on replica error / shutdown.
* ``GET /healthz`` — liveness + the serving status snapshot.
* ``POST /admin/stop`` — graceful stop (drain admitted work, then the
  coordinator broadcasts STOP to every replica). Loopback-guarded by
  the default bind address like everything else here.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from ..common import telemetry
from ..utils import env as env_cfg
from ..utils.logging import get_logger
from .batcher import (
    STATUS_DEADLINE, STATUS_ERROR, STATUS_OK, STATUS_SHUTDOWN,
    AdmissionQueue, ContinuousBatcher, InferenceRequest,
)

logger = get_logger()


class _Handler(BaseHTTPRequestHandler):
    server_version = "hvd-serving"
    # Keep-alive lets a looping client reuse its connection (and its
    # handler thread) across requests.
    protocol_version = "HTTP/1.1"

    def _send(self, code: int, doc: dict, extra_headers=()):
        payload = json.dumps(doc).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):  # noqa: N802 (http.server API)
        fe: "InferenceFrontend" = self.server.owner  # type: ignore[attr-defined]
        try:
            if self.path.startswith("/healthz"):
                self._send(200, fe.status())
            else:
                self._send(404, {"error": "try POST /v1/infer, "
                                 "GET /healthz, POST /admin/stop"})
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_POST(self):  # noqa: N802 (http.server API)
        fe: "InferenceFrontend" = self.server.owner  # type: ignore[attr-defined]
        try:
            if self.path.startswith("/admin/stop"):
                fe.request_stop()
                self._send(200, {"stopping": True})
                return
            if not self.path.startswith("/v1/infer"):
                self._send(404, {"error": "try POST /v1/infer"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                doc = json.loads(self.rfile.read(n) or b"null")
            except (ValueError, OSError) as e:
                self._send(400, {"error": f"bad request body: {e}"})
                return
            if (isinstance(doc, dict) and doc.get("stream")
                    and env_cfg.serving_stream_enabled()):
                fe.infer_stream(doc, self)
                return
            code, out = fe.infer(doc)
            hdrs = (("Retry-After", "1"),) if code == 429 else ()
            self._send(code, out, hdrs)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client hung up; the request future just gets dropped
        except Exception as e:  # a broken provider must not kill the server
            try:
                self._send(500, {"error": str(e)})
            except OSError:  # pragma: no cover - peer gone during the 500
                pass

    def log_message(self, fmt, *args):
        logger.debug("serving http: " + fmt, *args)


class InferenceFrontend:
    """Admission + HTTP surface. Owns the bounded queue and the
    batcher; the replica coordinator (serving/replicas.py) pulls batches
    out of it and completes the request futures."""

    def __init__(self, port: Optional[int] = None,
                 addr: Optional[str] = None,
                 registry: Optional[telemetry.MetricsRegistry] = None,
                 status_fn: Optional[Callable[[], dict]] = None,
                 stop_fn: Optional[Callable[[], None]] = None):
        self.registry = registry or telemetry.default_registry()
        self.queue = AdmissionQueue(env_cfg.serving_queue_depth(),
                                    registry=self.registry)
        self.batcher = ContinuousBatcher(
            self.queue,
            max_batch=env_cfg.serving_max_batch(),
            max_tokens=env_cfg.serving_max_batch_tokens(),
            max_delay_s=env_cfg.serving_max_delay_ms() / 1000.0,
            registry=self.registry)
        self.default_timeout = env_cfg.serving_request_timeout()
        self._status_fn = status_fn
        self._stop_fn = stop_fn
        self._stopping = threading.Event()
        # The election fence (serving/doors.py): serve() attaches a
        # DoorGuard when redundant doors are on. None = classic single
        # front door, never stale.
        self.door_guard = None
        self._m_latency = self.registry.histogram(
            "horovod_serving_request_seconds",
            "End-to-end request latency, admission to reply")
        self._m_chunks = self.registry.counter(
            "horovod_serving_streamed_chunks_total",
            "Streaming data frames written to clients")
        # Admitted-and-not-yet-answered, derived from the request
        # futures themselves (pruned on read): the programmatic
        # `submit()` path has no infer() handler to pair a decrement
        # with, so a counter would only ever go up.
        self._open: dict = {}
        self._inflight_lock = threading.Lock()
        self._inflight_fn = self._inflight_count
        self.registry.gauge(
            "horovod_serving_inflight_requests",
            "Admitted requests not yet answered",
        ).set_function(self._inflight_fn)
        self._httpd = None
        self._thread = None
        self.port = None
        if port is None:
            port = env_cfg.serving_port()
        if port >= 0:
            self._httpd = ThreadingHTTPServer(
                (addr if addr is not None else env_cfg.serving_addr(),
                 port), _Handler)
            self._httpd.daemon_threads = True
            self._httpd.owner = self  # type: ignore[attr-defined]
            self.port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="hvd-serving-http",
                daemon=True)

    def start(self) -> "InferenceFrontend":
        if self._thread is not None:
            self._thread.start()
            logger.info("serving front door on :%d (/v1/infer)", self.port)
        return self

    def stop(self):
        self._stopping.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(timeout=5)
            self._httpd = None
            self._thread = None
        self.queue.close()
        self.registry.gauge(
            "horovod_serving_inflight_requests",
        ).clear_function(self._inflight_fn)

    # -- admission -------------------------------------------------------
    def request_stop(self):
        self._stopping.set()
        if self._stop_fn is not None:
            self._stop_fn()

    @property
    def stopping(self) -> bool:
        return self._stopping.is_set()

    def submit(self, inputs, tokens: int = 1,
               timeout_s: Optional[float] = None, stream: bool = False,
               chunks: int = 1) -> Optional[InferenceRequest]:
        """Programmatic admission (the HTTP route and tests both land
        here). None = rejected (queue full, stopping, or this door's
        election epoch went stale — the fence in docs/serving.md
        "Redundant front doors")."""
        if self._stopping.is_set():
            return None
        if self.door_guard is not None and self.door_guard.stale():
            # A door that lost an election it did not participate in
            # must not admit against a budget the fleet re-leased.
            self.batcher.count("rejected")
            return None
        # A client may lower its deadline below the server default,
        # never raise it past it (the server bound is the operator's
        # overload guarantee).
        t = self.default_timeout if timeout_s is None else min(
            max(float(timeout_s), 0.001), self.default_timeout)
        req = InferenceRequest(inputs, tokens=tokens, timeout_s=t,
                               stream=stream, chunks=chunks)
        if not self.queue.offer(req):
            self.batcher.count("rejected")
            return None
        with self._inflight_lock:
            self._open[req.id] = req
        self._trace_admit(req)
        self._chaos_admit_hook()
        return req

    def _chaos_admit_hook(self):
        """killdoor drill point (common/fault_injection.py): one call
        per ACCEPTED request, flagged with whether this process is the
        active door. No guard = the classic single front door, which
        is by definition active."""
        try:
            from ..common import fault_injection

            inj = fault_injection.injector
            if inj.active:
                inj.check_door_admit(
                    self.door_guard.active if self.door_guard is not None
                    else True)
        except Exception:  # chaos plumbing must never fail admission
            pass

    def _trace_admit(self, req: InferenceRequest):
        """`serve.admit` instant in the flight recorder — pairs with
        the coordinator's serve.batch/forward/reply spans so one trace
        shows a request's whole life (docs/serving.md)."""
        try:
            from ..common import basics

            eng = basics.engine() if basics.is_initialized() else None
            if eng is not None:
                eng.tracer.instant("serve.admit", cat="serve",
                                   args={"req": req.id,
                                         "tokens": req.tokens})
        except Exception:  # tracing must never fail admission
            pass

    def _inflight_count(self) -> int:
        with self._inflight_lock:
            done = [rid for rid, r in self._open.items() if r.done]
            for rid in done:
                del self._open[rid]
            return len(self._open)

    @staticmethod
    def _parse_infer_doc(doc) -> "tuple":
        """(inputs, tokens, timeout_s, chunks) from a request body —
        the structured form or any bare JSON document as the inputs."""
        if isinstance(doc, dict) and ("inputs" in doc or "tokens" in doc
                                      or "timeout_s" in doc
                                      or "stream" in doc):
            return (doc.get("inputs"), doc.get("tokens", 1),
                    doc.get("timeout_s"), doc.get("chunks", 1))
        return doc, 1, None, 1

    def _reject(self) -> "tuple[int, dict]":
        """Why submit() said no, as an HTTP answer."""
        if self._stopping.is_set():
            return 503, {"error": "serving is stopping"}
        guard = self.door_guard
        if guard is not None and guard.stale():
            return 503, {"error": (
                "stale front door: lease epoch "
                f"{guard.epoch} superseded by epoch "
                f"{guard.current_epoch()}; retry another door")}
        return 429, {"error": "admission queue full; retry"}

    def infer(self, doc) -> "tuple[int, dict]":
        """Blocking request → (http_code, body). Runs on the handler
        thread; parks on the request future until completion or
        deadline."""
        inputs, tokens, timeout_s, _ = self._parse_infer_doc(doc)
        if self._stopping.is_set():
            return 503, {"error": "serving is stopping"}
        req = self.submit(inputs, tokens=tokens, timeout_s=timeout_s)
        if req is None:
            return self._reject()
        # Park until the deadline. A request STILL QUEUED at its
        # deadline is answered 504 right here (first-completion-wins
        # settles the race with a batcher take at the same instant);
        # one already dispatched gets a grace window for the in-flight
        # reply. The last-resort error completion only fires if the
        # serving loop itself died.
        req.wait(max(req.deadline - time.monotonic(), 0))
        if not req.done and not req.dispatched:
            if req.complete(None, STATUS_DEADLINE,
                            "deadline expired before dispatch"):
                self.batcher.count(STATUS_DEADLINE)
        elif not req.done and not req.wait(5.0):
            if req.complete(None, STATUS_ERROR, "serving loop stalled"):
                self.batcher.count(STATUS_ERROR)
        self._m_latency.observe(time.monotonic() - req.enqueued)
        if req.status == STATUS_OK:
            # The coordinator completes OK requests with
            # {"output", "weight_step"} so clients can prove which
            # weights answered them (the hot-swap acceptance check).
            body = req.result if isinstance(req.result, dict) else {
                "output": req.result}
            return 200, body
        return self._error_code(req.status), {
            "error": req.error or req.status or "replica error"}

    @staticmethod
    def _error_code(status) -> int:
        if status == STATUS_DEADLINE:
            return 504
        if status == STATUS_SHUTDOWN:
            return 503
        return 500

    def infer_stream(self, doc, handler):
        """Streaming request → ndjson frames over a chunked HTTP/1.1
        response (docs/serving.md "Streaming responses"). The handler
        thread drains the request's frame queue: one data frame per
        serving round, each carrying `weight_step`, then a terminal
        frame. Deadline/504 semantics are preserved: BEFORE the first
        frame the client gets a plain 504/5xx JSON answer exactly like
        unary; once bytes have flowed, a deadline or a failover ends
        the stream with a terminal error frame — never a silent hang
        (complete() always appends one)."""
        inputs, tokens, timeout_s, chunks = self._parse_infer_doc(doc)
        if self._stopping.is_set():
            handler._send(503, {"error": "serving is stopping"})
            return
        req = self.submit(inputs, tokens=tokens, timeout_s=timeout_s,
                          stream=True, chunks=chunks)
        if req is None:
            code, body = self._reject()
            hdrs = (("Retry-After", "1"),) if code == 429 else ()
            handler._send(code, body, hdrs)
            return
        # Wait for the FIRST frame up to the deadline; the status code
        # is still ours to choose until bytes hit the wire.
        first = req.next_chunk(max(req.deadline - time.monotonic(), 0))
        if first is None:
            if not req.done and not req.dispatched:
                if req.complete(None, STATUS_DEADLINE,
                                "deadline expired before dispatch"):
                    self.batcher.count(STATUS_DEADLINE)
            elif not req.done and not req.wait(5.0):
                if req.complete(None, STATUS_ERROR,
                                "serving loop stalled"):
                    self.batcher.count(STATUS_ERROR)
            first = req.next_chunk(5.0)
        self._m_latency.observe(time.monotonic() - req.enqueued)
        if first is None or first.get("final"):
            status = (first or {}).get("status", STATUS_ERROR)
            if status == STATUS_OK:
                # Completed without a data frame (e.g. streaming off
                # upstream): answer the final result as unary JSON.
                body = req.result if isinstance(req.result, dict) else {
                    "output": req.result}
                handler._send(200, body)
            else:
                handler._send(self._error_code(status), {
                    "error": (first or {}).get("error")
                    or req.error or str(status)})
            return
        handler.send_response(200)
        handler.send_header("Content-Type", "application/x-ndjson")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()

        def _write(frame: dict):
            data = (json.dumps(frame) + "\n").encode("utf-8")
            handler.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
            handler.wfile.flush()

        try:
            frame = first
            while True:
                if not frame.get("final"):
                    self._m_chunks.inc()
                _write(frame)
                if frame.get("final"):
                    break
                frame = req.next_chunk(
                    max(req.deadline - time.monotonic(), 0))
                if frame is None:
                    # Deadline passed mid-stream: terminate loudly.
                    if req.complete(None, STATUS_DEADLINE,
                                    "deadline expired mid-stream"):
                        self.batcher.count(STATUS_DEADLINE)
                    frame = req.next_chunk(5.0) or {
                        "final": True, "status": STATUS_DEADLINE,
                        "error": "deadline expired mid-stream"}
            handler.wfile.write(b"0\r\n\r\n")
            handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass  # client hung up mid-stream; the future settles alone

    # -- introspection ---------------------------------------------------
    def basic_status(self) -> dict:
        """The frontend's OWN state (the /serving view embeds this
        next to the replica-set state without duplicating it)."""
        return {
            "queue_depth": self.queue.depth(),
            "inflight": self._inflight_count(),
            "stopping": self._stopping.is_set(),
            "port": self.port,
        }

    def status(self) -> dict:
        st = self.basic_status()
        if self._status_fn is not None:
            try:
                st.update(self._status_fn())
            except Exception:  # pragma: no cover - status best-effort
                pass
        return st
