"""HTTP front door for the serving plane (rank 0 / standalone).

Reuses the `metrics_export` server plumbing — the same daemon-thread
``ThreadingHTTPServer`` shape, one handler thread per in-flight client
connection — so the front door costs nothing new architecturally. The
endpoint is unauthenticated and binds loopback by default
(``HOROVOD_SERVING_ADDR``), the `HOROVOD_METRICS_ADDR` posture.

Routes:

* ``POST /v1/infer`` — body ``{"inputs": <json>, "tokens": <int>?,
  "timeout_s": <float>?}`` (or any bare JSON document, taken as the
  inputs). Admission: a full queue answers **429** with ``Retry-After``
  (backpressure — the queue bound is ``HOROVOD_SERVING_QUEUE_DEPTH``);
  an admitted request parks the handler thread on the request future
  and answers **200** ``{"output": ..., "weight_step": ...}``, **504**
  when the per-request deadline expired (before OR after dispatch), or
  **500**/**503** on replica error / shutdown.
* ``GET /healthz`` — liveness + the serving status snapshot.
* ``POST /admin/stop`` — graceful stop (drain admitted work, then the
  coordinator broadcasts STOP to every replica). Loopback-guarded by
  the default bind address like everything else here.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from ..common import telemetry
from ..utils import env as env_cfg
from ..utils.logging import get_logger
from .batcher import (
    STATUS_DEADLINE, STATUS_ERROR, STATUS_OK, STATUS_SHUTDOWN,
    AdmissionQueue, ContinuousBatcher, InferenceRequest,
)

logger = get_logger()


class _Handler(BaseHTTPRequestHandler):
    server_version = "hvd-serving"
    # Keep-alive lets a looping client reuse its connection (and its
    # handler thread) across requests.
    protocol_version = "HTTP/1.1"

    def _send(self, code: int, doc: dict, extra_headers=()):
        payload = json.dumps(doc).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):  # noqa: N802 (http.server API)
        fe: "InferenceFrontend" = self.server.owner  # type: ignore[attr-defined]
        try:
            if self.path.startswith("/healthz"):
                self._send(200, fe.status())
            else:
                self._send(404, {"error": "try POST /v1/infer, "
                                 "GET /healthz, POST /admin/stop"})
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_POST(self):  # noqa: N802 (http.server API)
        fe: "InferenceFrontend" = self.server.owner  # type: ignore[attr-defined]
        try:
            if self.path.startswith("/admin/stop"):
                fe.request_stop()
                self._send(200, {"stopping": True})
                return
            if not self.path.startswith("/v1/infer"):
                self._send(404, {"error": "try POST /v1/infer"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                doc = json.loads(self.rfile.read(n) or b"null")
            except (ValueError, OSError) as e:
                self._send(400, {"error": f"bad request body: {e}"})
                return
            code, out = fe.infer(doc)
            hdrs = (("Retry-After", "1"),) if code == 429 else ()
            self._send(code, out, hdrs)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client hung up; the request future just gets dropped
        except Exception as e:  # a broken provider must not kill the server
            try:
                self._send(500, {"error": str(e)})
            except OSError:  # pragma: no cover - peer gone during the 500
                pass

    def log_message(self, fmt, *args):
        logger.debug("serving http: " + fmt, *args)


class InferenceFrontend:
    """Admission + HTTP surface. Owns the bounded queue and the
    batcher; the replica coordinator (serving/replicas.py) pulls batches
    out of it and completes the request futures."""

    def __init__(self, port: Optional[int] = None,
                 addr: Optional[str] = None,
                 registry: Optional[telemetry.MetricsRegistry] = None,
                 status_fn: Optional[Callable[[], dict]] = None,
                 stop_fn: Optional[Callable[[], None]] = None):
        self.registry = registry or telemetry.default_registry()
        self.queue = AdmissionQueue(env_cfg.serving_queue_depth(),
                                    registry=self.registry)
        self.batcher = ContinuousBatcher(
            self.queue,
            max_batch=env_cfg.serving_max_batch(),
            max_tokens=env_cfg.serving_max_batch_tokens(),
            max_delay_s=env_cfg.serving_max_delay_ms() / 1000.0,
            registry=self.registry)
        self.default_timeout = env_cfg.serving_request_timeout()
        self._status_fn = status_fn
        self._stop_fn = stop_fn
        self._stopping = threading.Event()
        self._m_latency = self.registry.histogram(
            "horovod_serving_request_seconds",
            "End-to-end request latency, admission to reply")
        # Admitted-and-not-yet-answered, derived from the request
        # futures themselves (pruned on read): the programmatic
        # `submit()` path has no infer() handler to pair a decrement
        # with, so a counter would only ever go up.
        self._open: dict = {}
        self._inflight_lock = threading.Lock()
        self._inflight_fn = self._inflight_count
        self.registry.gauge(
            "horovod_serving_inflight_requests",
            "Admitted requests not yet answered",
        ).set_function(self._inflight_fn)
        self._httpd = None
        self._thread = None
        self.port = None
        if port is None:
            port = env_cfg.serving_port()
        if port >= 0:
            self._httpd = ThreadingHTTPServer(
                (addr if addr is not None else env_cfg.serving_addr(),
                 port), _Handler)
            self._httpd.daemon_threads = True
            self._httpd.owner = self  # type: ignore[attr-defined]
            self.port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="hvd-serving-http",
                daemon=True)

    def start(self) -> "InferenceFrontend":
        if self._thread is not None:
            self._thread.start()
            logger.info("serving front door on :%d (/v1/infer)", self.port)
        return self

    def stop(self):
        self._stopping.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(timeout=5)
            self._httpd = None
            self._thread = None
        self.queue.close()
        self.registry.gauge(
            "horovod_serving_inflight_requests",
        ).clear_function(self._inflight_fn)

    # -- admission -------------------------------------------------------
    def request_stop(self):
        self._stopping.set()
        if self._stop_fn is not None:
            self._stop_fn()

    @property
    def stopping(self) -> bool:
        return self._stopping.is_set()

    def submit(self, inputs, tokens: int = 1,
               timeout_s: Optional[float] = None
               ) -> Optional[InferenceRequest]:
        """Programmatic admission (the HTTP route and tests both land
        here). None = rejected (queue full or stopping)."""
        if self._stopping.is_set():
            return None
        # A client may lower its deadline below the server default,
        # never raise it past it (the server bound is the operator's
        # overload guarantee).
        t = self.default_timeout if timeout_s is None else min(
            max(float(timeout_s), 0.001), self.default_timeout)
        req = InferenceRequest(inputs, tokens=tokens, timeout_s=t)
        if not self.queue.offer(req):
            self.batcher.count("rejected")
            return None
        with self._inflight_lock:
            self._open[req.id] = req
        self._trace_admit(req)
        return req

    def _trace_admit(self, req: InferenceRequest):
        """`serve.admit` instant in the flight recorder — pairs with
        the coordinator's serve.batch/forward/reply spans so one trace
        shows a request's whole life (docs/serving.md)."""
        try:
            from ..common import basics

            eng = basics.engine() if basics.is_initialized() else None
            if eng is not None:
                eng.tracer.instant("serve.admit", cat="serve",
                                   args={"req": req.id,
                                         "tokens": req.tokens})
        except Exception:  # tracing must never fail admission
            pass

    def _inflight_count(self) -> int:
        with self._inflight_lock:
            done = [rid for rid, r in self._open.items() if r.done]
            for rid in done:
                del self._open[rid]
            return len(self._open)

    def infer(self, doc) -> "tuple[int, dict]":
        """Blocking request → (http_code, body). Runs on the handler
        thread; parks on the request future until completion or
        deadline."""
        if isinstance(doc, dict) and ("inputs" in doc or "tokens" in doc
                                      or "timeout_s" in doc):
            inputs = doc.get("inputs")
            tokens = doc.get("tokens", 1)
            timeout_s = doc.get("timeout_s")
        else:
            inputs, tokens, timeout_s = doc, 1, None
        if self._stopping.is_set():
            return 503, {"error": "serving is stopping"}
        req = self.submit(inputs, tokens=tokens, timeout_s=timeout_s)
        if req is None:
            if self._stopping.is_set():
                return 503, {"error": "serving is stopping"}
            return 429, {"error": "admission queue full; retry"}
        # Park until the deadline. A request STILL QUEUED at its
        # deadline is answered 504 right here (first-completion-wins
        # settles the race with a batcher take at the same instant);
        # one already dispatched gets a grace window for the in-flight
        # reply. The last-resort error completion only fires if the
        # serving loop itself died.
        req.wait(max(req.deadline - time.monotonic(), 0))
        if not req.done and not req.dispatched:
            if req.complete(None, STATUS_DEADLINE,
                            "deadline expired before dispatch"):
                self.batcher.count(STATUS_DEADLINE)
        elif not req.done and not req.wait(5.0):
            if req.complete(None, STATUS_ERROR, "serving loop stalled"):
                self.batcher.count(STATUS_ERROR)
        self._m_latency.observe(time.monotonic() - req.enqueued)
        if req.status == STATUS_OK:
            # The coordinator completes OK requests with
            # {"output", "weight_step"} so clients can prove which
            # weights answered them (the hot-swap acceptance check).
            body = req.result if isinstance(req.result, dict) else {
                "output": req.result}
            return 200, body
        if req.status == STATUS_DEADLINE:
            return 504, {"error": req.error or "deadline expired"}
        if req.status == STATUS_SHUTDOWN:
            return 503, {"error": req.error or "serving stopped"}
        return 500, {"error": req.error or "replica error"}

    # -- introspection ---------------------------------------------------
    def basic_status(self) -> dict:
        """The frontend's OWN state (the /serving view embeds this
        next to the replica-set state without duplicating it)."""
        return {
            "queue_depth": self.queue.depth(),
            "inflight": self._inflight_count(),
            "stopping": self._stopping.is_set(),
            "port": self.port,
        }

    def status(self) -> dict:
        st = self.basic_status()
        if self._status_fn is not None:
            try:
                st.update(self._status_fn())
            except Exception:  # pragma: no cover - status best-effort
                pass
        return st
