"""Training-loop callbacks (Keras-style).

(ref: horovod/_keras/callbacks.py:22-192 — BroadcastGlobalVariables,
MetricAverage, LearningRateSchedule, LearningRateWarmup.)

JAX has no Model.fit, so these are small composable objects for custom
loops plus pure helpers (optax schedules for warmup).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from .common import basics
from .common.functions import broadcast_parameters
from .common.types import ReduceOp


class Callback:
    def on_train_begin(self, context: dict):
        pass

    def on_epoch_begin(self, epoch: int, context: dict):
        pass

    def on_epoch_end(self, epoch: int, context: dict):
        pass

    def on_batch_begin(self, batch: int, context: dict):
        pass

    def on_batch_end(self, batch: int, context: dict):
        pass


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast initial params from root so all ranks start identical
    (ref: _keras/callbacks.py:22-46; torch broadcast_parameters)."""

    def __init__(self, root_rank: int = 0, params_key: str = "params"):
        self.root_rank = root_rank
        self.params_key = params_key
        self._done = False

    def on_train_begin(self, context: dict):
        if not self._done and self.params_key in context:
            context[self.params_key] = broadcast_parameters(
                context[self.params_key], self.root_rank
            )
            self._done = True


class MetricAverageCallback(Callback):
    """Average epoch metrics over ranks before logging
    (ref: _keras/callbacks.py:48-88)."""

    def __init__(self, metrics_key: str = "metrics"):
        self.metrics_key = metrics_key

    def on_epoch_end(self, epoch: int, context: dict):
        from . import ops

        metrics = context.get(self.metrics_key)
        if not metrics:
            return
        context[self.metrics_key] = {
            k: float(np.asarray(ops.allreduce(np.asarray(v, dtype=np.float64),
                                              op=ReduceOp.AVERAGE)))
            for k, v in metrics.items()
        }


class MetricsCallback(Callback):
    """Log a one-line telemetry summary every `interval` batches: step
    time, allreduce MB/s, response-cache hit rate, window goodput% and
    exposed-comm ms per batch (docs/metrics.md, docs/goodput.md).
    `log_fn` overrides the destination (default: the horovod logger at
    INFO); only `root_only` rank 0 logs by default so an N-rank job
    prints one line, not N."""

    def __init__(self, interval: int = 100, log_fn=None, root_only: bool = True,
                 registry=None):
        from .common import telemetry

        self._logger = telemetry.StepSummaryLogger(
            interval, log_fn, root_only, registry)

    def on_batch_end(self, batch: int, context: dict):
        self._logger.step()


class LearningRateScheduleCallback(Callback):
    """Multiply base LR by `multiplier(epoch)` (ref: _keras/callbacks.py:
    90-132). Works with a mutable lr holder dict: {"lr": float}."""

    def __init__(self, lr_holder: Dict[str, float], multiplier: Callable[[float], float],
                 start_epoch: int = 0, end_epoch: Optional[int] = None,
                 staircase: bool = True):
        self.holder = lr_holder
        self.base = lr_holder.get("lr", 0.0)
        self.multiplier = multiplier
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase

    def on_epoch_begin(self, epoch: int, context: dict):
        if epoch < self.start_epoch:
            return
        if self.end_epoch is not None and epoch >= self.end_epoch:
            return
        self.holder["lr"] = self.base * self.multiplier(epoch)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Linear warmup from lr/size to lr over warmup_epochs
    (ref: _keras/callbacks.py:134-192: gradual warmup of Goyal et al.)."""

    def __init__(self, lr_holder: Dict[str, float], warmup_epochs: int = 5,
                 momentum_correction: bool = True, steps_per_epoch: Optional[int] = None,
                 verbose: int = 0):
        self.warmup_epochs = warmup_epochs
        size = basics.size() if basics.is_initialized() else 1

        def multiplier(epoch):
            if epoch >= warmup_epochs:
                return 1.0
            alpha = (epoch + 1) / float(warmup_epochs)
            return 1.0 / size * (1 + alpha * (size - 1))

        super().__init__(lr_holder, multiplier, start_epoch=0,
                         end_epoch=warmup_epochs + 1, staircase=False)


def warmup_schedule(base_lr: float, warmup_steps: int, size: Optional[int] = None):
    """Optax-style schedule: lr/size → lr·1 linear warmup then constant —
    the idiomatic JAX spelling of LearningRateWarmupCallback."""
    import optax

    n = size if size is not None else (basics.size() if basics.is_initialized() else 1)
    return optax.join_schedules(
        [optax.linear_schedule(base_lr / n, base_lr, warmup_steps),
         optax.constant_schedule(base_lr)],
        [warmup_steps],
    )
