"""ZeRO-1/2 sharded optimizer state across both data planes (ROADMAP
item 3; Rajbhandari et al., "ZeRO: Memory Optimizations Toward Training
Trillion Parameter Models"; docs/running.md "ZeRO sharded optimizer
state").

`DistributedOptimizer(zero=1|2)` stops keeping a full replica of the
inner optimizer's state (Adam moments etc.) on every data rank. Instead
each rank owns a contiguous shard of the FLATTENED state and the update
becomes reduce-scatter → shard update → allgather:

* **Traced plane** (inside jit/shard_map over the resolved data axis —
  the `hvd.resolve_axis` rule): gradients flatten into one accumulation
  buffer, `lax.psum_scatter` reduces it and leaves each device exactly
  its owned 1/n slice (the wire never carries the full gradient twice —
  ZeRO-2's gradient sharding falls out of the lowering), the inner
  optimizer updates that slice only, and `lax.all_gather` rebuilds the
  full update. Every `ZeroState` leaf carries a leading shard dimension
  (per-device `(1, ...)`, global `(n, ...)`), so one uniform
  `PartitionSpec(axis)` prefix shards the whole state tree — the
  NamedSharding idiom that scales to pod meshes — and the global state
  is an ordinary sharded jax.Array that `JaxState`/`CheckpointManager`
  snapshot unchanged.
* **Eager plane** (process mode): leaf ownership is the
  `shard_ranges` balanced-by-bytes cut from common/checkpoint.py —
  the same deterministic tiling the checkpoint writer uses — over the
  gradient leaves; grads ride the engine's grouped allreduce (native
  kernels, wire codecs and the engine's own error feedback apply), the
  owned leaves update as one flat accumulation segment, and the
  updated segments allgather back (raw full-width floats, so
  reassembly is bitwise).

**Error feedback as optimizer state** (Karimireddy et al. 2019): with
`error_feedback=True` the traced wire cast (PR 15's stateless bf16/fp16
cast, plus the int8-with-scale lane) gains the eager codec's accuracy
story — the quantization residual `e - decode(encode(e))` is carried in
`ZeroState.residual` across steps and added back before the next
encode, so the shipped values telescope to the true sum. Under ZeRO the
residual lives on the allgather (update) leg and is sharded with the
moments — 1/n memory — while the scatter leg keeps the stateless cast
(its input is the full local gradient, so a residual there would cost
full-gradient memory, defeating ZeRO). Without ZeRO the residual is
gradient-sized and corrects the allreduce itself.

Supported inner optimizers: elementwise transforms (sgd, momentum,
adam(w), rmsprop, ...). Transforms that need cross-tree statistics
(e.g. `clip_by_global_norm`) see only the local shard here — apply
them outside the wrapper.
"""
from __future__ import annotations

import threading
import time
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from ..common import basics, telemetry
from ..common.checkpoint import shard_ranges
from ..common.types import ReduceOp
from ..utils.compat import axis_index as _axis_index, axis_size as _axis_size

_STATE_BYTES_HELP = (
    "Optimizer-state bytes this rank holds: mode=\"sharded\" is the "
    "measured owned-shard footprint, mode=\"replicated\" is what a "
    "full-replica optimizer would hold (docs/running.md \"ZeRO sharded "
    "optimizer state\")")


class ZeroState(NamedTuple):
    """Traced-plane optimizer state.

    ``inner`` — the inner optimizer's state over the owned flat shard;
    under ZeRO every leaf carries a leading shard dim (per-device
    ``(1, ...)``, global ``(n, ...)``) so a uniform ``P(axis)`` prefix
    spec shards the whole tree. In EF-only mode (``zero=0``) ``inner``
    is the unmodified full-tree state (replicated, spec ``P()``).

    ``residual`` — the error-feedback residual, ``(1, k)`` per device
    over the owned update shard (ZeRO) or ``(1, total)`` over the flat
    gradient buffer (EF-only); ``None`` when error feedback is off, so
    disabled mode carries zero extra leaves.
    """

    inner: Any
    residual: Optional[Any]


@jax.tree_util.register_pytree_node_class
class ZeroEagerState:
    """Eager-plane (process mode) state: the inner optimizer's state
    over this rank's flat owned segment, plus the static leaf-range
    cut ``[lo, hi)`` of ``shard_ranges(leaf_bytes, nshards)`` it was
    built from (aux data, not leaves — checkpoint trees stay
    arrays-only)."""

    def __init__(self, inner, lo: int, hi: int, nshards: int):
        self.inner = inner
        self.lo = int(lo)
        self.hi = int(hi)
        self.nshards = int(nshards)

    def tree_flatten(self):
        return (self.inner,), (self.lo, self.hi, self.nshards)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], *aux)

    def __repr__(self):  # pragma: no cover - debug aid
        return (f"ZeroEagerState(leaves[{self.lo}:{self.hi}] of "
                f"{self.nshards} shards)")


# -- shared flatten/pack helpers ---------------------------------------
def _is_tracer(x) -> bool:
    try:
        return isinstance(x, jax.core.Tracer)
    except Exception:  # pragma: no cover
        return False


def _acc_dtype(leaves):
    """The accumulation dtype of the flat buffer: the widest leaf dtype
    (the grouped_allreduce convention)."""
    return jnp.result_type(*[jnp.asarray(l).dtype for l in leaves])


def _metas(leaves):
    return [(np.shape(l), int(np.prod(np.shape(l), dtype=np.int64)),
             jnp.asarray(l).dtype) for l in leaves]


def _pack(leaves, acc):
    return jnp.concatenate([jnp.ravel(jnp.asarray(l)).astype(acc)
                            for l in leaves]) if leaves else jnp.zeros(
                                (0,), acc)


def _unpack(flat, metas):
    out, off = [], 0
    for shape, size, dt in metas:
        out.append(jnp.reshape(flat[off:off + size], shape).astype(dt))
        off += size
    return out


def _state_nbytes(tree) -> int:
    return sum(int(np.prod(np.shape(l), dtype=np.int64))
               * jnp.asarray(l).dtype.itemsize
               for l in jax.tree.leaves(tree))


def _abstract_nbytes(tree) -> int:
    return sum(int(np.prod(l.shape, dtype=np.int64)) * l.dtype.itemsize
               for l in jax.tree.leaves(tree))


def _replicated_state_bytes(inner, params) -> int:
    """What a full-replica inner optimizer would hold per rank —
    measured abstractly (jax.eval_shape costs no memory)."""
    try:
        return _abstract_nbytes(jax.eval_shape(inner.init, params))
    except Exception:  # pragma: no cover - exotic inner transforms
        return 0


# -- telemetry / status -------------------------------------------------
_status_lock = threading.Lock()
_status: dict = {}


def _note_status(**kw):
    """Record the live ZeRO configuration for the `/status` `zero`
    section (consumed by engine.status(), rendered by hvdtop)."""
    with _status_lock:
        _status.update(kw)
        _status["wall"] = time.time()


def status_snapshot() -> dict:
    """The `zero` section of `/status`; {} until a ZeRO/EF optimizer
    initializes in this process."""
    with _status_lock:
        return dict(_status)


def _set_state_gauges(sharded: int, replicated: int):
    telemetry.gauge("horovod_optimizer_state_bytes", _STATE_BYTES_HELP,
                    labels={"mode": "sharded"}).set(int(sharded))
    telemetry.gauge("horovod_optimizer_state_bytes", _STATE_BYTES_HELP,
                    labels={"mode": "replicated"}).set(int(replicated))


# -- traced plane -------------------------------------------------------
def _update_wire_mode(x) -> Optional[str]:
    """Codec decision for the allgather (update) leg: same gates as the
    gradient-side policy — int8 lane first (opt-in), then the bf16/fp16
    cast — on fp32 payloads at or above the min-bytes floor. Trace-time
    like every traced knob."""
    from ..ops.traced import _traced_int8_enabled, _traced_wire_dtype

    if _traced_int8_enabled(x, ReduceOp.SUM):
        return "int8"
    dt = _traced_wire_dtype(x, ReduceOp.SUM)
    if dt is not None:
        return "fp16" if dt == jnp.float16 else "bf16"
    return None


def _encode_gather(h, ax, n):
    """Encode the owned update shard for the allgather leg, gather, and
    decode — returns (full updates buffer, this device's decoded own
    contribution) so the caller can form the EF residual. The decode of
    the own shard is BITWISE what every receiver computes for it, so
    the residual accounts exactly the shipped error."""
    mode = _update_wire_mode(h)
    if mode == "int8":
        from ..ops.traced import int8_encode

        q, scale = int8_encode(h.astype(jnp.float32))
        qs = lax.all_gather(q, ax, tiled=True)           # (n·k,) int8
        ss = lax.all_gather(scale, ax)                   # (n,) fp32
        k = h.shape[0]
        full = (qs.astype(jnp.float32).reshape(n, k)
                * ss[:, None]).reshape(n * k).astype(h.dtype)
        dec_own = (q.astype(jnp.float32) * scale).astype(h.dtype)
        return full, dec_own
    if mode in ("bf16", "fp16"):
        dt = jnp.float16 if mode == "fp16" else jnp.bfloat16
        w = h.astype(dt)
        return (lax.all_gather(w, ax, tiled=True).astype(h.dtype),
                w.astype(h.dtype))
    return lax.all_gather(h, ax, tiled=True), h


def _shard_geometry(total: int, n: int):
    pad = (-total) % n
    return pad, (total + pad) // n


def _traced_zero_init(inner, params_leaves, ax, error_feedback: bool):
    n = _axis_size(ax)
    idx = _axis_index(ax)
    acc = _acc_dtype(params_leaves)
    flat_p = _pack(params_leaves, acc)
    pad, k = _shard_geometry(flat_p.shape[0], n)
    if pad:
        flat_p = jnp.pad(flat_p, (0, pad))
    p_shard = lax.dynamic_slice(flat_p, (idx * k,), (k,))
    st = jax.tree.map(lambda l: jnp.asarray(l)[None], inner.init(p_shard))
    res = jnp.zeros((1, k), acc) if error_feedback else None
    return ZeroState(st, res)


def _traced_zero_update(inner, state, grads, params, ax, op, prescale,
                        postscale, error_feedback: bool, extra):
    from ..ops.traced import _scale, _traced_wire_dtype

    g_leaves, treedef = jax.tree.flatten(grads)
    p_leaves = jax.tree.leaves(params)
    metas = _metas(g_leaves)
    acc = _acc_dtype(g_leaves)
    n = _axis_size(ax)
    idx = _axis_index(ax)

    flat_g = _scale(_pack(g_leaves, acc), prescale)
    total = flat_g.shape[0]
    pad, k = _shard_geometry(total, n)
    if pad:
        flat_g = jnp.pad(flat_g, (0, pad))
    # Scatter leg: the reduce-scatter IS the gradient reduction — each
    # device receives only its owned 1/n slice (ZeRO-2's gradient
    # sharding). Without error feedback the stateless wire cast applies
    # exactly as the PR 15 allreduce policy does. WITH error feedback
    # the scatter leg ships full width and the whole compression budget
    # moves to the allgather leg below: a scatter-side residual would
    # be full-gradient-sized (the cast error is per-contributor,
    # pre-reduction), while the allgather-side residual is the owned
    # (k,) shard — the only leg correctable at 1/n memory.
    wire_dt = None if error_feedback else _traced_wire_dtype(flat_g, op)
    if wire_dt is not None:
        g_shard = lax.psum_scatter(
            flat_g.astype(wire_dt), ax, scatter_dimension=0, tiled=True,
        ).astype(acc)
    else:
        g_shard = lax.psum_scatter(flat_g, ax, scatter_dimension=0,
                                   tiled=True)
    if op == ReduceOp.AVERAGE:
        g_shard = g_shard / n
    g_shard = _scale(g_shard, postscale)

    flat_p = _pack(p_leaves, acc)
    if pad:
        flat_p = jnp.pad(flat_p, (0, pad))
    p_shard = lax.dynamic_slice(flat_p, (idx * k,), (k,))

    inner_state = jax.tree.map(lambda l: l[0], state.inner)
    upd_shard, new_inner = inner.update(g_shard, inner_state, p_shard,
                                        **extra)

    # Allgather leg, with the sharded EF residual: h = update + carry;
    # ship encode(h); next step's carry is h - decode(encode(h)).
    if error_feedback:
        h = upd_shard + state.residual[0]
    else:
        h = upd_shard
    full, dec_own = _encode_gather(h, ax, n)
    new_res = (h - dec_own) if error_feedback else None
    if pad:
        full = full[:total]
    updates = jax.tree.unflatten(treedef, _unpack(full, metas))
    new_state = ZeroState(
        jax.tree.map(lambda l: l[None], new_inner),
        new_res[None] if error_feedback else None)
    return updates, new_state


def _traced_ef_init(inner, params_leaves, params, ax):
    """EF without ZeRO: full inner state (replicated), plus a
    per-device residual over the whole flat gradient buffer."""
    total = sum(int(np.prod(np.shape(l), dtype=np.int64))
                for l in params_leaves)
    acc = _acc_dtype(params_leaves)
    return ZeroState(inner.init(params),
                     jnp.zeros((1, total), acc))


def _traced_ef_update(inner, state, grads, params, ax, op, prescale,
                      postscale, extra):
    """EF-only traced allreduce: the stateless wire cast becomes
    cast-with-carry — e = grads + residual is encoded, the psum ships
    the encoded values, and the new residual is e - decode(encode(e)),
    so the summed wire values telescope to the true gradient sum."""
    from ..ops.traced import (
        _scale,
        _traced_int8_enabled,
        _traced_wire_dtype,
        int8_encode,
    )

    g_leaves, treedef = jax.tree.flatten(grads)
    metas = _metas(g_leaves)
    acc = _acc_dtype(g_leaves)
    n = _axis_size(ax)
    flat = _scale(_pack(g_leaves, acc), prescale)
    e = flat + state.residual[0]
    if _traced_int8_enabled(e, op):
        q, scale = int8_encode(e.astype(jnp.float32))
        qs = lax.all_gather(q, ax)
        ss = lax.all_gather(scale, ax)
        red = jnp.sum(qs.astype(jnp.float32) * ss[:, None],
                      axis=0).astype(acc)
        dec_own = (q.astype(jnp.float32) * scale).astype(acc)
    else:
        wire_dt = _traced_wire_dtype(e, op)
        if wire_dt is not None:
            w = e.astype(wire_dt)
            red = lax.psum(w, ax).astype(acc)
            dec_own = w.astype(acc)
        else:
            red = lax.psum(e, ax)
            dec_own = e
    new_res = e - dec_own
    if op == ReduceOp.AVERAGE:
        red = red / n
    red = _scale(red, postscale)
    red_tree = jax.tree.unflatten(treedef, _unpack(red, metas))
    upd, new_inner = inner.update(red_tree, state.inner, params, **extra)
    return upd, ZeroState(new_inner, new_res[None])


# -- eager plane --------------------------------------------------------
def _eager_world():
    if basics.is_initialized() and basics.mode() == "process":
        return basics.size(), basics.rank()
    # Mesh-mode concrete / uninitialized: a single controller holds one
    # copy of everything — sharding a single process's state frees
    # nothing, so the cut is the trivial 1-way cut (documented).
    return 1, 0


# Block size (elements) of the eager ownership cut. Ownership is
# element-granular over the FLAT buffer — a leaf-granularity cut
# cannot balance a tree dominated by one big leaf (the embedding
# matrix problem) and would break the measured (n-1)/n memory claim —
# but the cut itself is still the checkpoint writer's `shard_ranges`
# balanced-by-bytes walk, applied to fixed-size blocks of the buffer.
_ZERO_BLOCK = 512


def _eager_cut(total_elems: int, itemsize: int, n: int):
    """Per-rank element ranges [lo, hi) of the flat state buffer."""
    nblocks = max((total_elems + _ZERO_BLOCK - 1) // _ZERO_BLOCK, 1)
    ranges = shard_ranges([_ZERO_BLOCK * itemsize] * nblocks, n)
    return [(min(a * _ZERO_BLOCK, total_elems),
             min(b * _ZERO_BLOCK, total_elems)) for a, b in ranges]


def _eager_zero_init(inner, params):
    leaves, _ = jax.tree.flatten(params)
    if not leaves:
        raise ValueError("zero mode needs a non-empty params pytree")
    n, r = _eager_world()
    acc = _acc_dtype(leaves)
    total = sum(m[1] for m in _metas(leaves))
    lo, hi = _eager_cut(total, acc.itemsize, n)[r]
    seg = _pack(leaves, acc)[lo:hi]
    inner_state = inner.init(seg)
    sharded = _state_nbytes(inner_state)
    replicated = _replicated_state_bytes(inner, params)
    _set_state_gauges(sharded, replicated)
    _note_status(enabled=True, plane="eager", world=n,
                 owned_range=[lo, hi], total_elems=total,
                 sharded_state_bytes=sharded,
                 replicated_state_bytes=replicated,
                 error_feedback=False)
    return ZeroEagerState(inner_state, lo, hi, n)


def _eager_zero_update(inner, state, grads, params, op, prescale,
                       postscale, extra):
    from ..ops import allgather, grouped_allreduce

    g_leaves, treedef = jax.tree.flatten(grads)
    p_leaves = jax.tree.leaves(params)
    metas = _metas(g_leaves)
    acc = _acc_dtype(g_leaves)
    n = state.nshards
    lo, hi = state.lo, state.hi
    # Gradient reduction rides the engine's grouped path untouched —
    # native kernels, transports and wire codecs (with the engine's own
    # error feedback) all apply. The coordinator fuses these like any
    # gradient exchange; each rank then updates only its owned slice.
    red = grouped_allreduce(g_leaves, op=op, name="zero.grads",
                            prescale_factor=prescale,
                            postscale_factor=postscale)
    g_seg = _pack(red, acc)[lo:hi]
    p_seg = _pack(p_leaves, acc)[lo:hi]
    upd_seg, new_inner = inner.update(g_seg, state.inner, p_seg, **extra)
    if n == 1:
        full = upd_seg
    else:
        # Updated-segment exchange: raw full-width floats (allgatherv
        # handles the variable per-rank lengths), so every rank decodes
        # the SAME bytes — reassembly is bitwise across ranks. One
        # sentinel element pads each rank's payload so an empty owned
        # range (more ranks than blocks) still gathers.
        payload = np.concatenate(
            [np.asarray(upd_seg, dtype=acc).ravel(), np.zeros(1, acc)])
        gathered = np.asarray(allgather(payload, name="zero.updates"))
        total = sum(m[1] for m in metas)
        ranges = _eager_cut(total, acc.itemsize, n)
        parts, off = [], 0
        for a, b in ranges:
            parts.append(gathered[off:off + (b - a)])
            off += (b - a) + 1
        full = jnp.asarray(np.concatenate(parts))
    updates = jax.tree.unflatten(treedef, _unpack(full, metas))
    return updates, ZeroEagerState(new_inner, lo, hi, n)


# -- checkpoint / elasticity helpers ------------------------------------
def recut_state(state: ZeroState, params, new_world: int) -> ZeroState:
    """Re-cut a GLOBAL stacked traced ``ZeroState`` (leaves ``(n, k)``
    vectors / ``(n,)`` scalars, e.g. as materialized by
    ``JaxState.save``/``CheckpointManager``) from world size n to m.
    Content is bitwise-preserved: only the zero padding at the flat
    tail is re-sized. Shard-scalar leaves (optax counts) are identical
    across shards by construction; shard 0's value is broadcast."""
    total = sum(int(np.prod(np.shape(l), dtype=np.int64))
                for l in jax.tree.leaves(params))
    any_leaf = jax.tree.leaves(state)
    if not any_leaf:
        return state
    n = int(np.shape(any_leaf[0])[0])
    _, k = _shard_geometry(total, n)
    pad_m, k2 = _shard_geometry(total, new_world)

    def cut(l):
        a = np.asarray(l)
        if a.ndim == 1 and a.shape == (n,):
            return np.full((new_world,), a[0], a.dtype)
        if a.ndim >= 2 and a.shape[0] == n and a.shape[1] == k:
            flat = a.reshape((n * k,) + a.shape[2:])[:total]
            if pad_m:
                flat = np.concatenate(
                    [flat, np.zeros((pad_m,) + flat.shape[1:], a.dtype)])
            return flat.reshape((new_world, k2) + a.shape[2:])
        raise ValueError(
            f"unrecognized ZeroState leaf layout {a.shape} for world "
            f"{n} / shard {k} — only elementwise inner transforms "
            "(leaves (n, k) or (n,)) re-cut")

    return jax.tree.map(cut, state)


def eager_state_to_global(inner, state: ZeroEagerState, params):
    """Allgather every rank's owned flat segment into the replicated
    single-shard form (the state as if one rank owned every leaf) —
    every rank ends up holding identical trees, restoring the
    CheckpointManager's replicated-snapshot invariant so the existing
    durability plane checkpoints eager ZeRO state unchanged."""
    from ..ops import allgather

    p_leaves = jax.tree.leaves(params)
    acc = _acc_dtype(p_leaves)
    n = state.nshards
    if n == 1:
        return jax.tree.map(np.asarray, state.inner)
    total = sum(int(np.prod(np.shape(l), dtype=np.int64))
                for l in p_leaves)
    ranges = _eager_cut(total, acc.itemsize, n)
    varying = _varying_mask(inner, acc)
    leaves_s = jax.tree.leaves(state.inner)
    out = []
    for j, (leaf, var) in enumerate(zip(leaves_s, varying)):
        if not var:
            out.append(np.asarray(leaf))
            continue
        arr = np.asarray(leaf)
        payload = np.concatenate(
            [arr.ravel(), np.zeros(1, arr.dtype)])
        gathered = np.asarray(allgather(payload, name=f"zero.state.{j}"))
        parts, off = [], 0
        for a, b in ranges:
            parts.append(gathered[off:off + (b - a)])
            off += (b - a) + 1
        out.append(np.concatenate(parts))
    return jax.tree.unflatten(jax.tree.structure(state.inner), out)


def eager_state_from_global(inner, global_inner, params,
                            world: Optional[int] = None,
                            rank: Optional[int] = None) -> ZeroEagerState:
    """Re-cut a replicated single-shard inner state (from
    `eager_state_to_global`, a checkpoint restore, or a world-size
    change) to this rank's owned segment — the n→m restore path.
    Bitwise: the flat per-element arrays are sliced verbatim."""
    if world is None or rank is None:
        world, rank = _eager_world()
    p_leaves = jax.tree.leaves(params)
    acc = _acc_dtype(p_leaves)
    total = sum(int(np.prod(np.shape(l), dtype=np.int64))
                for l in p_leaves)
    lo, hi = _eager_cut(total, acc.itemsize, world)[rank]
    varying = _varying_mask(inner, acc)
    out = [np.asarray(l)[lo:hi] if var else np.asarray(l)
           for l, var in zip(jax.tree.leaves(global_inner), varying)]
    return ZeroEagerState(
        jax.tree.unflatten(jax.tree.structure(global_inner), out),
        lo, hi, world)


def _varying_mask(inner, acc):
    """Which inner-state leaves scale with the flat segment length
    (cut-able moments) vs shared scalars (optax counts) — probed
    abstractly by comparing init structures at two segment lengths."""
    a = jax.tree.leaves(jax.eval_shape(inner.init,
                                       jax.ShapeDtypeStruct((1,), acc)))
    b = jax.tree.leaves(jax.eval_shape(inner.init,
                                       jax.ShapeDtypeStruct((2,), acc)))
    return [x.shape != y.shape for x, y in zip(a, b)]


# -- ergonomics ---------------------------------------------------------
def state_specs(axis_name: str, zero: bool = True):
    """The shard_map in/out PartitionSpec prefix for a
    DistributedOptimizer state under jit: with ZeRO every leaf carries
    the leading shard dim, so one uniform ``P(axis)`` shards the whole
    tree; EF-only states shard just the residual."""
    from jax.sharding import PartitionSpec as P

    if zero:
        return P(axis_name)
    return ZeroState(inner=P(), residual=P(axis_name))


def _pick_mesh_axis(mesh, axis_name: Optional[str]) -> str:
    """Mirror of `hvd.resolve_axis` for a concrete mesh: explicit wins,
    then the init axis, then the canonical data axes, then the first
    mesh axis (1-D meshes)."""
    names = tuple(mesh.axis_names)
    if axis_name is not None:
        return axis_name
    an = basics.axis_name() if basics.is_initialized() else None
    for cand in ((an,) if an else ()) + ("dp", "hvd"):
        if cand in names:
            return cand
    return names[0]


def zero_init(tx, params, mesh, axis_name: Optional[str] = None):
    """Initialize a ZeRO/EF-wrapped `DistributedOptimizer` state as a
    GLOBAL sharded array over `mesh` — the out-of-jit spelling of
    "init runs inside shard_map" (traced init needs the axis size,
    which a plain `tx.init(params)` outside a trace cannot know). `tx`
    is the WRAPPED transformation (`DistributedOptimizer(inner,
    zero=...)`). Returns stacked leaves ((n, ...) global) sharded over
    the data axis; pass them into the training step with in_specs
    `hvd.zero_state_specs(axis)`."""
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import set_mesh, shard_map

    ax = _pick_mesh_axis(mesh, axis_name)
    f = shard_map(lambda p: tx.init(p), mesh=mesh, in_specs=(P(),),
                  out_specs=state_specs(ax, zero=True))
    with set_mesh(mesh):
        state = jax.jit(f)(params)
    n = int(np.prod([mesh.shape[a] for a in
                     (ax if isinstance(ax, tuple) else (ax,))]))
    # Measured from the actual state: the global stacked tree is what a
    # full replica would hold per rank (modulo the flat-tail padding);
    # each device keeps a 1/n share — the number that drops (n-1)/n.
    replicated = _state_nbytes(state)
    sharded = replicated // max(n, 1)
    _set_state_gauges(sharded, replicated)
    _note_status(enabled=True, plane="traced", world=n, axis=ax,
                 sharded_state_bytes=sharded,
                 replicated_state_bytes=replicated)
    return state


# -- the optax wrapper (called by DistributedOptimizer) -----------------
def zero_optimizer(
    optimizer: optax.GradientTransformation,
    *,
    op: ReduceOp = ReduceOp.AVERAGE,
    axis_name: Optional[str] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    stage: int = 1,
    error_feedback: bool = False,
) -> optax.GradientTransformationExtraArgs:
    """The ZeRO/EF gradient transformation behind
    `DistributedOptimizer(zero=..., error_feedback=...)`. `stage` 0
    means EF-only (replicated state, residual-corrected wire cast)."""
    if stage not in (0, 1, 2):
        raise ValueError(f"zero stage must be 0/1/2, got {stage!r}")
    if stage == 0 and not error_feedback:
        raise ValueError("zero_optimizer needs stage>=1 or error_feedback")

    def _resolved_axis():
        from ..ops import resolve_axis

        ax = resolve_axis(axis_name)
        if ax is None and basics.is_initialized():
            an = basics.axis_name()
            from ..ops import _bound_axes

            ax = an if an in _bound_axes() else None
        return ax

    def init_fn(params):
        leaves = jax.tree.leaves(params)
        if leaves and _is_tracer(leaves[0]):
            ax = _resolved_axis()
            if ax is None:
                raise ValueError(
                    "traced ZeRO init needs a bound data axis — init "
                    "inside shard_map over the mesh, or use "
                    "hvd.optim.zero_init(tx, params, mesh)")
            if stage:
                st = _traced_zero_init(optimizer, leaves, ax,
                                       error_feedback)
            else:
                st = _traced_ef_init(optimizer, leaves, params, ax)
            _note_status(enabled=True, plane="traced",
                         stage=stage, error_feedback=error_feedback)
            return st
        if stage:
            st = _eager_zero_init(optimizer, params)
            with _status_lock:
                _status["stage"] = stage
            return st
        # EF-only, concrete: the residual corrects the TRACED wire
        # cast; eagerly it stays zeros (the engine codec carries its
        # own residual store) but the state shape matches the traced
        # plane so one checkpoint format serves both.
        total = sum(int(np.prod(np.shape(l), dtype=np.int64))
                    for l in leaves)
        return ZeroState(optimizer.init(params),
                         jnp.zeros((1, total), _acc_dtype(leaves)))

    def update_fn(grads, state, params=None, **extra):
        from .distributed import _stage_traced_step_marker
        from ..common import goodput

        if params is None:
            raise ValueError(
                "DistributedOptimizer(zero=...) updates need params= "
                "(the owned shard is sliced from them)")
        led = goodput.active()
        leaves = jax.tree.leaves(grads)
        # Constant gradients under jit (e.g. a closed-over pytree) are
        # not tracers, but the params always are — either means we are
        # inside a trace and must lower to the collective ops.
        traced = any(_is_tracer(l)
                     for l in leaves + jax.tree.leaves(params))
        if led is not None and led.enabled:
            if traced:
                _stage_traced_step_marker()
            else:
                led.auto_step("optim")
        if traced:
            ax = _resolved_axis()
            if ax is None:
                raise ValueError(
                    "traced ZeRO update needs a bound data axis; wrap "
                    "the step in shard_map over the data axis")
            if stage:
                return _traced_zero_update(
                    optimizer, state, grads, params, ax, op,
                    prescale_factor, postscale_factor, error_feedback,
                    extra)
            return _traced_ef_update(
                optimizer, state, grads, params, ax, op,
                prescale_factor, postscale_factor, extra)
        if stage:
            return _eager_zero_update(
                optimizer, state, grads, params, op, prescale_factor,
                postscale_factor, extra)
        # EF-only, concrete: plain engine reduction + inner update.
        from .distributed import _allreduce_grads

        red = _allreduce_grads(grads, op, axis_name, prescale_factor,
                               postscale_factor, None, False)
        upd, new_inner = optimizer.update(red, state.inner, params,
                                          **extra)
        return upd, ZeroState(new_inner, state.residual)

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)
