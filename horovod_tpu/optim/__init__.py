"""Optimizer wrappers: the allreduce-before-update transformation and
its ZeRO-sharded / error-feedback variant (docs/running.md)."""
from . import distributed, zero
from .distributed import (
    DistributedGradientTape,
    DistributedOptimizer,
    distributed_value_and_grad,
)
from .zero import (
    ZeroEagerState,
    ZeroState,
    eager_state_from_global,
    eager_state_to_global,
    recut_state,
    state_specs,
    zero_init,
    zero_optimizer,
)
