"""DistributedOptimizer / DistributedGradientTape for JAX.

TPU-native re-design of the reference optimizer wrappers:
  - torch `_DistributedOptimizer` (ref: horovod/torch/optimizer.py:32-207):
    hooks fire async allreduces per gradient, `step()` synchronizes.
  - TF `_DistributedOptimizer`/`DistributedGradientTape`
    (ref: horovod/tensorflow/__init__.py:289-332,507-572) with the
    average-splitting pre/postscale logic (ref: __init__.py:242-274).

In JAX, optimizers are pure gradient transformations (optax), so the
wrapper is itself an optax transformation that allreduces the incoming
gradient pytree before the inner optimizer sees it. Under jit, the
allreduce lowers to ICI psum ops that XLA overlaps with the backward
pass — the same overlap the reference gets from per-layer async hooks,
achieved by the compiler instead of a background thread.

`backward_passes_per_step` local accumulation maps to optax.MultiSteps
wrapping (accumulate locally, communicate once per effective step),
matching the reference semantics (ref: optimizer.py backward_passes_per_step).
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import optax

from ..common import basics
from ..common.types import ReduceOp
from ..ops import allreduce as _allreduce_dispatch
from ..ops.compression import Compression, NoneCompressor
from ..ops.traced import allreduce_pytree


def _allreduce_grads(grads, op, axis_name, prescale, postscale, compression, fuse):
    comp = compression or Compression.none

    def one(g):
        c, ctx = comp.compress(g)
        r = _allreduce_dispatch(
            c, op=op, prescale_factor=prescale, postscale_factor=postscale,
            axis_name=axis_name,
        )
        return comp.decompress(r, ctx)

    leaves, treedef = jax.tree.flatten(grads)
    if fuse and leaves and _is_tracer(leaves[0]):
        from ..ops import resolve_axis
        from ..ops.traced import grouped_allreduce

        # The shared axis-resolution rule (docs/running.md "Traced
        # collectives"): on a 2-D data×model mesh this picks the DATA
        # axis only, so the fused gradient psum composes with tp/sp/pp
        # kernels without configuration.
        ax = resolve_axis(axis_name) or basics.axis_name()
        cs_ctx = [comp.compress(g) for g in leaves]
        red = grouped_allreduce(
            [c for c, _ in cs_ctx], ax, op,
            prescale, postscale,
        )
        out = [comp.decompress(r, ctx) for r, (_, ctx) in zip(red, cs_ctx)]
        return jax.tree.unflatten(treedef, out)
    return jax.tree.map(one, grads)


def _is_tracer(x) -> bool:
    try:
        return isinstance(x, jax.core.Tracer)
    except Exception:  # pragma: no cover
        return False


def _goodput_mark(idx):
    """Host side of the traced step marker: runs once per EXECUTED
    step. The ledger is re-read here so a plane toggled after
    compilation is honored at run time."""
    from ..common import goodput

    led = goodput.active()
    if led is not None and led.enabled and int(idx) == 0:
        led.auto_step("optim")


def _stage_traced_step_marker():
    """Goodput demarcation for TRACED optimizer updates, at the host
    call boundary (docs/goodput.md). The update body runs once at trace
    time, so calling auto_step here directly would count one step per
    COMPILATION; instead a jax.debug.callback is staged into the
    compiled program and fires on the host each time the jitted step
    executes. Under shard_map every shard runs the body, so the marker
    is gated on the all-axes-origin shard (summed axis_index == 0 over
    every bound axis); under plain jit/pjit the program is logical and
    the callback fires once per call.

    Known limitation (multi-controller pods): debug callbacks fire
    only for a process's LOCAL shards, and the origin shard lives on
    process 0 — so on a one-process-per-host mesh only rank 0's
    ledger is auto-demarcated by this marker. Multi-controller loops
    should use the explicit `hvd.step()` scope (or elastic commits),
    which demarcate every process; the single-controller regime this
    marker serves is where neither exists inside a jitted loop."""
    from ..ops import _bound_axes
    from ..utils.compat import axis_index as _axis_index

    idx = jnp.int32(0)
    for ax in _bound_axes():
        idx = idx + _axis_index(ax).astype(jnp.int32)
    jax.debug.callback(_goodput_mark, idx)


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    op: ReduceOp = ReduceOp.AVERAGE,
    compression=None,
    backward_passes_per_step: int = 1,
    axis_name: Optional[str] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    fuse: bool = False,
    zero: Optional[int] = None,
    error_feedback: Optional[bool] = None,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so gradients are allreduced before the
    update (ref: horovod/torch/optimizer.py:337-414 DistributedOptimizer
    factory; horovod/tensorflow/__init__.py:289-332).

    `zero` shards the inner optimizer's state over the resolved data
    axis ZeRO-style (docs/running.md "ZeRO sharded optimizer state"):
    traced updates lower to reduce-scatter → owned-shard update →
    allgather, eager updates cut leaf ownership with the checkpoint
    writer's `shard_ranges` tiling. `None` defers to
    HOROVOD_ZERO_SHARDING (default off); True means stage 1. Stages 1
    and 2 share the state layout — under jit the reduce-scatter
    lowering already never materializes the full reduced gradient, so
    the traced plane is effectively stage 2 either way.

    `error_feedback` carries the traced wire-cast quantization residual
    (bf16/fp16/int8 lanes) across steps as optimizer state — sharded
    with the moments under ZeRO — restoring the eager codec's accuracy
    story for jitted loops. With both off this wrapper is byte-for-byte
    the pre-ZeRO transformation (disabled mode pays nothing)."""
    if zero is None:
        from ..utils import env as env_cfg

        zero = env_cfg.zero_sharding_default()
    zero = int(zero)
    if error_feedback is None:
        error_feedback = False
    if zero or error_feedback:
        from .zero import zero_optimizer

        tx = zero_optimizer(
            optimizer, op=op, axis_name=axis_name,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            stage=zero, error_feedback=bool(error_feedback),
        )
        if backward_passes_per_step > 1:
            tx = optax.MultiSteps(
                tx, every_k_schedule=backward_passes_per_step)
        return tx

    def init_fn(params):
        return optimizer.init(params)

    def update_fn(grads, state, params=None, **extra):
        # Goodput step demarcation (docs/goodput.md): every eager
        # optimizer update is one training step. Under jit this body
        # runs once at trace time, so traced updates stage a
        # jax.debug.callback that fires per EXECUTED step at the host
        # call boundary instead (jitted loops get goodput_ratio too).
        # The ledger check comes first: with the plane off (or before
        # init) at trace time the update path must not pay even the
        # tree flatten — and stages no callback (an explicit
        # `hvd.step()` scope still works for programs that enable the
        # plane after compiling).
        from ..common import goodput

        led = goodput.active()
        if led is not None and led.enabled:
            leaves = jax.tree.leaves(grads)
            if leaves and _is_tracer(leaves[0]):
                _stage_traced_step_marker()
            else:
                led.auto_step("optim")
        red = _allreduce_grads(
            grads, op, axis_name, prescale_factor, postscale_factor,
            compression, fuse,
        )
        return optimizer.update(red, state, params, **extra)

    tx = optax.GradientTransformationExtraArgs(init_fn, update_fn)
    if backward_passes_per_step > 1:
        # Accumulate locally; communicate on the boundary step
        # (ref: optimizer.py backward_passes_per_step semantics).
        tx = optax.MultiSteps(tx, every_k_schedule=backward_passes_per_step)
    return tx


class DistributedGradientTape:
    """API-parity shim of TF's DistributedGradientTape
    (ref: horovod/tensorflow/__init__.py:507-572): wraps a jax
    value_and_grad function so .gradient() allreduces."""

    def __init__(
        self,
        fun: Callable,
        op: ReduceOp = ReduceOp.AVERAGE,
        compression=None,
        axis_name: Optional[str] = None,
        has_aux: bool = False,
    ):
        self._vg = jax.value_and_grad(fun, has_aux=has_aux)
        self._op = op
        self._compression = compression
        self._axis = axis_name

    def gradient(self, *args, **kwargs):
        val, grads = self._vg(*args, **kwargs)
        red = _allreduce_grads(
            grads, self._op, self._axis, 1.0, 1.0, self._compression, False
        )
        return val, red


def distributed_value_and_grad(
    fun: Callable,
    op: ReduceOp = ReduceOp.AVERAGE,
    axis_name: Optional[str] = None,
    has_aux: bool = False,
    fuse: bool = True,
    compression=None,
):
    """jax.value_and_grad + gradient allreduce in one transform — the
    idiomatic JAX spelling of DistributedGradientTape."""
    vg = jax.value_and_grad(fun, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        val, grads = vg(*args, **kwargs)
        red = _allreduce_grads(grads, op, axis_name, 1.0, 1.0, compression, fuse)
        return val, red

    return wrapped
