"""The asynchronous collective engine: background thread + cycle loop.

Re-implementation of the reference's core runtime (ref: horovod/common/
operations.cc): `Engine.start` spawns the background thread
(ref: InitializeHorovodOnce, operations.cc:620-666); each cycle sleeps
``HOROVOD_CYCLE_TIME`` ms, negotiates ready tensors through the
controller, and executes the resulting (fused) responses
(ref: RunLoopOnce, operations.cc:566-616; PerformOperation,
operations.cc:253-330). Framework threads enqueue work and wait on
handles (ref: EnqueueTensorAllreduce..., operations.cc:840-1068;
HandleManager, horovod/torch/handle_manager.h).

On TPU this engine serves the *eager* path (process mode). The traced
path (ops/traced.py) needs none of it: under jit, XLA plays the role of
the background thread, the fusion buffer and the response cache at once.

Pipelined execution (docs/running.md "Pipelined execution"): the
background loop no longer executes responses inline. Each non-fence
response carries a coordinator-assigned channel id; the loop hands it to
that channel's executor thread (per-channel FIFO — the cross-rank
ordering invariant that keeps concurrent collectives from deadlocking)
and immediately re-enters negotiation, so the control plane overlaps the
data plane. JOIN/BARRIER/ERROR/shutdown and autotune parameter-sync are
fences that drain every channel first; an executor HorovodInternalError
kills the whole engine and finalizes every pending handle. Cycles are
event-driven: an enqueue wakes the loop immediately, making
HOROVOD_CYCLE_TIME a max-coalescing delay instead of a latency floor.
"""
from __future__ import annotations

import os
import queue as queue_mod
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common import compression, goodput as goodput_mod, telemetry, tracing
from ..common.exceptions import HorovodInternalError, TransportError
from ..common.message import Request, RequestType, Response, ResponseType
from ..common.types import ReduceOp, Status, StatusType, to_wire_dtype
from ..utils import clock
from ..utils import env as env_cfg
from ..utils.logging import get_logger
from .controller import Controller
from .tensor_queue import TensorQueue, TensorTableEntry
from .timeline import (
    MEMCPY_IN_FUSION_BUFFER,
    MEMCPY_OUT_FUSION_BUFFER,
    Timeline,
)

logger = get_logger()


def _scale_np(arr: np.ndarray, factor: float) -> np.ndarray:
    """Scale preserving dtype; integer tensors scale in float64 then cast
    back so AVERAGE (postscale 1/size) doesn't zero them out
    (ref: ScaleBuffer dispatches int types through double,
    collective_operations.h:89-125)."""
    if np.issubdtype(arr.dtype, np.integer):
        return (arr.astype(np.float64) * factor).astype(arr.dtype)
    return arr * np.asarray(factor, dtype=arr.dtype)


class HandleManager:
    """(ref: horovod/torch/handle_manager.{h,cc})

    `wait` reports the time the caller actually BLOCKED to the goodput
    ledger (docs/goodput.md): a handle whose op completed while the
    caller computed costs ~0 here, so overlapped communication never
    reads as exposed-comm badput."""

    def __init__(self, goodput=None):
        self._lock = threading.Lock()
        self._next = 0
        self._results: Dict[int, Tuple[Status, Optional[np.ndarray]]] = {}
        self._events: Dict[int, threading.Event] = {}
        self._goodput = goodput

    def allocate(self) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._events[h] = threading.Event()
            return h

    def mark_done(self, handle: int, status: Status, result: Optional[np.ndarray]):
        with self._lock:
            ev = self._events.get(handle)
            self._results[handle] = (status, result)
        if ev is not None:
            ev.set()

    def poll(self, handle: int) -> bool:
        with self._lock:
            return handle in self._results

    def wait(self, handle: int, timeout: Optional[float] = None):
        ev = self._events.get(handle)
        if ev is not None and not ev.is_set():
            # Exposed communication: only the blocked portion counts.
            # The is_set() fast path keeps already-complete (overlapped)
            # waits at zero cost and zero attribution.
            gp = self._goodput
            if gp is not None and gp.enabled:
                t0 = time.monotonic()
                done = ev.wait(timeout)
                gp.note_exposed(time.monotonic() - t0)
            else:
                done = ev.wait(timeout)
            if not done:
                raise TimeoutError(f"handle {handle} did not complete")
        with self._lock:
            if handle not in self._results:
                # Never allocated (or already waited on): a clear error
                # instead of the bare KeyError `_results.pop` used to
                # throw from deep inside the manager.
                raise ValueError(f"unknown handle {handle}")
            status, result = self._results.pop(handle)
            self._events.pop(handle, None)
        if not status.ok():
            raise HorovodInternalError(status.reason)
        return result


# Fence response types: executed inline on the background thread after
# every channel drains. JOIN resets controller join state, BARRIER is a
# control-plane collective, ERROR must observe a settled engine so the
# failure it reports is attributable.
_FENCE_TYPES = frozenset((
    ResponseType.JOIN,
    ResponseType.BARRIER,
    ResponseType.ERROR,
))

_EXEC_STOP = object()


class _ChannelExecutor:
    """Per-channel response executor: a worker thread draining a FIFO
    queue. Every rank dispatches the same responses to the same channel
    in the same order (the coordinator-assigned channel id rides the
    Response wire message), so matching collectives always pair up
    across ranks even with several channels in flight at once."""

    def __init__(self, engine: "Engine", channel: int):
        self.engine = engine
        self.channel = channel
        self.queue: "queue_mod.Queue" = queue_mod.Queue()
        # Tensor names of the response being executed right now (surfaced
        # by /status as the per-channel in-flight view).
        self.current: Optional[List[str]] = None
        self.gauge = engine.registry.gauge(
            "horovod_executor_queue_depth",
            "Responses queued on a channel executor",
            labels={"channel": str(channel)})
        self.gauge.set_function(self.depth)
        self.thread = threading.Thread(
            target=self._loop, name=f"hvd-exec-{channel}", daemon=True)
        self.thread.start()

    def depth(self) -> int:
        return self.queue.qsize()

    def _loop(self):
        eng = self.engine
        while True:
            resp = self.queue.get()
            if resp is _EXEC_STOP:
                break
            try:
                # After a fatal error, drain without executing: the
                # queued responses' entries are finalized by the dying
                # background loop, and a broken mesh can't serve them.
                if eng._fatal_error is None:
                    self.current = list(resp.tensor_names)
                    # Tracing: executor-queue dwell — dispatch to
                    # pickup, the head-of-line wait the channel lanes
                    # exist to bound.
                    disp = getattr(resp, "_dispatch_ns", None)
                    if disp is not None and eng.tracer.enabled:
                        eng.tracer.emit(
                            "exec.queue_wait", tracing.CAT_EXEC, disp,
                            clock.mono_ns() - disp,
                            trace_id=resp.trace_id,
                            args={"channel": self.channel})
                    eng._perform_operation(resp)
            except HorovodInternalError as exc:
                # _perform_operation already failed THIS response's
                # entries; latch the error so the background loop dies
                # and finalizes every other pending handle on every
                # channel.
                eng._latch_fatal(exc)
            except BaseException as exc:  # pragma: no cover - defensive
                eng._latch_fatal(HorovodInternalError(str(exc)))
            finally:
                self.current = None
                eng._response_done()


class Engine:
    def __init__(
        self,
        rank: int = 0,
        size: int = 1,
        local_rank: int = 0,
        local_size: int = 1,
        cross_rank: int = 0,
        cross_size: int = 1,
        backend=None,
        scope: Optional[str] = None,
        registry: Optional[telemetry.MetricsRegistry] = None,
    ):
        # Rendezvous scope for the TCP mesh (subset communicators use a
        # ranks-derived scope; None = env / default world scope).
        self._scope = scope
        self.rank = rank
        self.size = size
        self.local_rank = local_rank
        self.local_size = local_size
        self.cross_rank = cross_rank
        self.cross_size = cross_size
        self._explicit_backend = backend
        self.backend = None
        self.controller: Optional[Controller] = None
        self.param_manager = None
        self.op_manager = None
        # Telemetry: one-process-per-rank jobs use the process default
        # registry; the in-process multi-rank test harness passes one
        # registry per engine so per-"rank" accounting stays separable.
        self.registry = registry if registry is not None else telemetry.default_registry()
        self._exporters: list = []
        self._last_cycle_ts: Optional[float] = None
        self._m_cycle = self.registry.histogram(
            "horovod_cycle_seconds",
            "Engine cycle work duration (sleep excluded)")
        self._m_responses = self.registry.counter(
            "horovod_responses_total", "Fused responses executed")
        self._m_resp_tensors = self.registry.histogram(
            "horovod_response_tensors",
            "Tensors per fused response", min_exp=0, max_exp=12)
        self._m_resp_bytes = self.registry.histogram(
            "horovod_response_bytes",
            "Payload bytes per fused response", min_exp=0, max_exp=34)
        self._m_op_counters: Dict[str, Tuple] = {}
        self._m_op_latency: Dict[str, telemetry.Histogram] = {}
        self.tensor_queue = TensorQueue(registry=self.registry)
        # Pull gauges attach only after their backing state exists: on
        # the process-default registry a scraper can sample mid-__init__
        # (elastic shutdown+init window), and a callback hitting a
        # not-yet-assigned attribute would report NaN instead of 0.
        # Every pull callback this engine registers on the (possibly
        # process-default) registry, remembered so shutdown can detach
        # CONDITIONALLY: clear_function(fn) only detaches if this engine
        # is still the current owner. An unconditional clear would
        # freeze a REPLACEMENT engine's gauges whenever teardown of the
        # old engine overlaps init of the new one (the bug
        # HeartbeatMonitor.stop already fixed for the heartbeat-age
        # gauges).
        self._gauge_fns: Dict[str, object] = {
            "horovod_tensor_queue_depth": self.tensor_queue_depth,
            "horovod_last_cycle_age_seconds": self._last_cycle_age,
        }
        self.registry.gauge(
            "horovod_tensor_queue_depth",
            "Tensors currently pending in the queue",
        ).set_function(self.tensor_queue_depth)
        self.registry.gauge(
            "horovod_last_cycle_age_seconds",
            "Seconds since the background loop last completed a cycle",
        ).set_function(self._last_cycle_age)
        # Tracing plane (common/tracing.py, docs/tracing.md): the
        # always-on flight recorder behind the span API. Per-engine
        # like the registry so the in-process multi-rank harness keeps
        # per-"rank" recorders separable.
        self.tracer = tracing.Tracer(registry=self.registry)
        # Goodput plane (common/goodput.py, docs/goodput.md): process-
        # shared on the default registry (the ledger outlives this
        # engine across elastic resets), private on injected registries
        # so the in-process harness keeps per-"rank" accounting.
        self.goodput = goodput_mod.for_engine(self.registry, rank,
                                              tracer=self.tracer)
        self.handles = HandleManager(goodput=self.goodput)
        self._pm_dumped = False
        self.timeline = (Timeline(registry=self.registry) if rank == 0
                         else Timeline(use_env=False, registry=self.registry))
        self.cycle_time_s = env_cfg.cycle_time_ms() / 1000.0
        self._thread: Optional[threading.Thread] = None
        self._shutdown_requested = threading.Event()
        self._initialized = threading.Event()
        self._init_error: Optional[BaseException] = None
        # -- pipelined execution state ---------------------------------
        # Channel executors, created for the local HOROVOD_NUM_CHANNELS
        # at loop start and lazily for any higher channel id the
        # coordinator assigns (its env wins — the id rides the wire).
        # Only the background thread creates/dispatches; other threads
        # just snapshot the dict for /status.
        self._executors: Dict[int, _ChannelExecutor] = {}
        # Dispatched-but-unfinished responses across all channels; the
        # condition gates the backpressure window and fence drains.
        self._inflight = 0
        self._inflight_cond = threading.Condition()
        self._max_inflight = env_cfg.max_inflight_responses()
        # First HorovodInternalError anywhere in the engine (executor,
        # background loop, liveness monitor); latched once, kills the
        # whole engine and is the reason EVERY pending handle fails
        # with — first-cause attribution (read without the lock on hot
        # paths — benign).
        self._fatal_error: Optional[HorovodInternalError] = None
        # Liveness plane (common/health.py); armed by the background
        # loop once the backend exists, when heartbeats are enabled.
        self._health = None
        # Health plane (common/timeseries.py + common/alerts.py,
        # docs/health.md): the on-box sampler ring and the alert engine
        # evaluated on its ticks; armed by start() after init succeeds.
        self.sampler = None
        self.alerts = None
        self._fleet_alerts = None
        # Events plane (common/events.py, docs/events.md): the
        # process-wide lifecycle journal; rank 0 folds every rank's
        # batches (FleetEvents) for the /events chronicle. Wired by
        # start(); None when HOROVOD_EVENTS_BUFFER=0.
        self._fleet_events = None
        # Event-driven cycles: enqueues (and shutdown) set the event so
        # HOROVOD_CYCLE_TIME is a max-coalescing delay, not a floor.
        self._wake = threading.Event()
        self._event_cycles = env_cfg.cycle_event_driven()
        self.tensor_queue.set_wakeup(self._wake.set)
        self._m_wake = {
            reason: self.registry.counter(
                "horovod_cycle_wakeups_total",
                "Background-loop cycle starts by wake reason",
                labels={"reason": reason})
            for reason in ("enqueue", "timeout", "spin", "shutdown")
        }
        self._gauge_fns["horovod_inflight_responses"] = (
            lambda: self._inflight)
        self.registry.gauge(
            "horovod_inflight_responses",
            "Responses dispatched to channel executors and not yet done",
        ).set_function(self._gauge_fns["horovod_inflight_responses"])
        self._op_counter: Dict[str, int] = {}
        self._counter_lock = threading.Lock()
        # Cycles that carried at least one negotiated response — the
        # observable proxy for "how many engine round-trips did a batch
        # of requests take" (a fused batch costs ~1; a serialized stream
        # of N requests costs N). Bindings' fusion tests assert on it.
        self.response_cycles = 0
        # Persistent fusion buffer, one per (channel, dtype), grown to
        # the largest fused payload seen (ref: FusionBufferManager's
        # per-device persistent buffer, fusion_buffer_manager.h:30-56).
        # Each channel executor touches only its own keys.
        self._fusion_storage: Dict[Tuple[int, str], np.ndarray] = {}
        # Wire compression (docs/running.md "Wire compression"):
        # per-tensor error-feedback residuals + the telemetry sink the
        # codec scope threads to every data-plane encode site. Both
        # engine-owned, so an elastic reset (fresh Engine on every
        # rank) zeroes residuals consistently across the job.
        self._error_feedback = compression.ErrorFeedback()
        self._comp_stats = compression.CompressionStats(self.registry)

    # ------------------------------------------------------------------
    def tensor_queue_depth(self) -> int:
        return self.tensor_queue.size()

    def _last_cycle_age(self) -> float:
        ts = self._last_cycle_ts
        return (time.monotonic() - ts) if ts is not None else -1.0

    def _observe_op(self, op_name: str, seconds: float):
        h = self._m_op_latency.get(op_name)
        if h is None:
            h = self.registry.histogram(
                "horovod_op_latency_seconds",
                "Data-plane op execution latency by backend implementation",
                labels={"op": op_name},
            )
            self._m_op_latency[op_name] = h
        h.observe(seconds)

    def _record_response(self, resp_type: ResponseType, ntensors: int,
                         nbytes: int):
        self._m_responses.inc()
        self._m_resp_tensors.observe(ntensors)
        self._m_resp_bytes.observe(nbytes)
        ent = self._m_op_counters.get(resp_type.name)
        if ent is None:
            low = resp_type.name.lower()
            ent = (
                self.registry.counter(
                    f"horovod_{low}_tensors_total",
                    f"Tensors processed by {resp_type.name} responses"),
                self.registry.counter(
                    f"horovod_{low}_bytes_total",
                    f"Input payload bytes moved by {resp_type.name}"),
            )
            self._m_op_counters[resp_type.name] = ent
        ent[0].inc(ntensors)
        ent[1].inc(nbytes)

    def status(self) -> dict:
        """Live job state for the /status endpoint — the running version
        of the stall inspector's post-mortem (docs/metrics.md)."""
        st = {
            "rank": self.rank,
            "size": self.size,
            "queue_depth": self.tensor_queue.size(),
            "pending_tensors": self.tensor_queue.pending_names(),
            "last_cycle_age_seconds": self._last_cycle_age(),
            "response_cycles": self.response_cycles,
            "inflight_responses": self._inflight,
        }
        channels = {}
        # list() snapshot: the background thread may lazily insert an
        # executor while an exporter thread renders /status.
        for ch, ex in sorted(list(self._executors.items())):
            cur = ex.current  # snapshot: executor may finish mid-read
            channels[str(ch)] = {
                "queue_depth": ex.depth(),
                "executing": list(cur) if cur else [],
            }
        st["channels"] = channels
        # Transport plane (docs/running.md "Transports"): per-peer
        # route view — which peers have a live shm overlay and what the
        # current HOROVOD_TRANSPORT route is.
        backend = self.backend
        if backend is not None and hasattr(backend, "transport_status"):
            try:
                st["transports"] = backend.transport_status()
            except Exception:  # pragma: no cover - status best-effort
                pass
        # Wire compression (docs/running.md "Wire compression"): the
        # live policy knobs, error-feedback footprint, and bytes saved
        # per codec — "is the wire actually narrower" at a glance.
        st["wire_compression"] = {
            "mode": env_cfg.wire_compression_mode(),
            "min_bytes": env_cfg.wire_compression_min_bytes(),
            "int8_latency": env_cfg.wire_compression_int8(),
            "residual_tensors": self._error_feedback.size(),
            "residual_bytes": self._error_feedback.nbytes(),
            "bytes_saved": self._comp_stats.saved_snapshot(),
        }
        # Native core (docs/native.md): built / loaded / ABI / which
        # kernels run native vs numpy fallback — "is the data plane
        # actually GIL-free" at a glance.
        from ..cc import native as native_mod

        st["native"] = native_mod.status()
        # Tracing plane: recorder depth / drop count / last dump — the
        # "is the flight recorder actually capturing" view.
        trace = self.tracer.status()
        ctrl0 = self.controller
        if ctrl0 is not None and ctrl0.trace_collector is not None:
            trace["collected"] = ctrl0.trace_collector.status()
        st["trace"] = trace
        health = self._health
        if health is not None:
            st["health"] = health.status()
        # Health plane (docs/health.md): sampler ring state + latched
        # alert verdicts, the "is anything wrong RIGHT NOW" section.
        # Locals: shutdown nulls these fields concurrently with status
        # scrapes.
        sampler, alert_eng = self.sampler, self.alerts
        fleet_alerts = self._fleet_alerts
        if sampler is not None:
            st["timeseries"] = sampler.status()
        if alert_eng is not None:
            alerts_st = alert_eng.status()
            st["alerts"] = {
                "stale": alerts_st["stale"],
                "firing": alerts_st["firing"],
            }
            if fleet_alerts is not None:
                st["alerts"]["fleet"] = \
                    fleet_alerts.snapshot()["firing_by_rule"]
        # Goodput plane (docs/goodput.md): the step/badput ledger in
        # compact form — "how much of this job became training".
        st["goodput"] = self.goodput.status_summary()
        # Events plane (docs/events.md): ring state + a compact tail of
        # the newest lifecycle events — "what just happened to this
        # job" without opening the full /events chronicle.
        from ..common import events as _events

        ev_rec = _events.active()
        if ev_rec is not None and ev_rec.enabled:
            st["events"] = {**ev_rec.status(), "tail": ev_rec.tail()}
        # Durability plane: last committed/pending checkpoint step,
        # last error (docs/checkpoint.md). The manager is owned by the
        # elastic run loop, not the engine — report whichever one is
        # live in this process.
        from ..common import checkpoint as _ckpt

        ckpt_mgr = _ckpt.current()
        if ckpt_mgr is not None:
            st["checkpoint"] = ckpt_mgr.status()
        # ZeRO plane (docs/running.md "ZeRO sharded optimizer state"):
        # live once a sharded/EF DistributedOptimizer initializes in
        # this process — owned like `checkpoint` above by its own
        # module, merely surfaced here.
        from ..optim import zero as _zero

        zero_st = _zero.status_snapshot()
        if zero_st:
            st["zero"] = zero_st
        # Serving plane (docs/serving.md): role, rounds, weight step,
        # eviction verdicts — live while serve() runs in this process,
        # like `checkpoint` above. The replica set is process-global,
        # not engine-owned: it survives the engine swap an eviction's
        # subset re-mesh performs.
        from ..serving import replicas as _serving

        plane = _serving.current()
        if plane is not None:
            st["serving"] = plane.status()
        ctrl = self.controller
        if ctrl is not None and ctrl.is_coordinator:
            now = time.monotonic()
            pending = {}
            try:
                for name, (t0, ready) in list(ctrl.stall_inspector.pending.items()):
                    ready = set(ready)
                    pending[name] = {
                        "age_seconds": now - t0,
                        "ready_ranks": sorted(ready),
                        "missing_ranks": sorted(set(range(self.size)) - ready),
                    }
            except RuntimeError:  # table resized under us; next scrape wins
                pass
            st["negotiating"] = pending
            if ctrl.fleet is not None:
                st["fleet"] = ctrl.fleet.snapshot()
        return st

    # -- health-plane views (docs/health.md) ----------------------------
    def _timeseries_view(self) -> dict:
        """The /timeseries body: ring state, derived rates/quantiles/
        windows for every series, raw scalar points."""
        sampler = self.sampler
        if sampler is None:
            return {"enabled": False}
        return sampler.store.view()

    def _alerts_view(self) -> dict:
        """The /alerts body: this rank's rule states plus (coordinator)
        the fleet fold naming which rank each alert fires on."""
        alert_eng, fleet_alerts = self.alerts, self._fleet_alerts
        body: dict = {
            "local": alert_eng.status() if alert_eng is not None
            else {"enabled": False},
        }
        if fleet_alerts is not None:
            body["fleet"] = fleet_alerts.snapshot()
        return body

    # -- goodput plane view (docs/goodput.md) ---------------------------
    def _goodput_view(self) -> dict:
        """The /goodput body: this rank's full ledger plus (coordinator)
        the per-rank badput attribution folded from the goodput scalars
        already riding the telemetry piggyback — which rank's exposed
        comm is eating the fleet."""
        body: dict = {"local": self.goodput.view()}
        ctrl = self.controller
        if ctrl is not None and ctrl.fleet is not None:
            per_rank = {}
            for r, scalars in sorted(ctrl.fleet.ranks().items()):
                per_rank[str(r)] = {
                    "steps": scalars.get(
                        "horovod_goodput_steps_total", 0.0),
                    "exposed_comm_seconds": scalars.get(
                        "horovod_exposed_comm_seconds_total", 0.0),
                    "ckpt_stall_seconds": scalars.get(
                        "horovod_ckpt_stall_seconds_total", 0.0),
                    "restart_downtime_seconds": scalars.get(
                        "horovod_restart_downtime_seconds_total", 0.0),
                    "replayed_steps": scalars.get(
                        "horovod_replayed_steps_total", 0.0),
                    "goodput_ratio": scalars.get(
                        "horovod_goodput_ratio"),
                }
            fleet: dict = {"ranks": per_rank}
            if per_rank:
                worst = max(per_rank.items(),
                            key=lambda kv: kv[1]["exposed_comm_seconds"])
                fleet["max_exposed_comm_rank"] = int(worst[0])
                fleet["max_exposed_comm_seconds"] = \
                    worst[1]["exposed_comm_seconds"]
            body["fleet"] = fleet
        return body

    # -- events plane view (docs/events.md) -----------------------------
    def _events_view(self) -> dict:
        """The /events body: this rank's ring state + tail, plus
        (coordinator) the fleet fold — the merged causally-ordered
        chronicle with per-rank clock-skew annotations."""
        from ..common import events as events_mod

        rec = events_mod.active()
        if rec is None or not rec.enabled:
            return {"local": {"enabled": False}}
        body: dict = {"local": {**rec.status(),
                                "events": rec.tail(n=rec.capacity)}}
        fleet = self._fleet_events
        if fleet is not None:
            # Render-time freshness fold (the collect_local idiom):
            # rank 0's own events never ride the piggyback, and skew
            # estimates improve as heartbeats sample.
            from ..utils import clock as _clock

            fleet.ingest(self.rank, rec.snapshot(),
                         anchor=_clock.anchor_meta())
            health = self._health
            if health is not None:
                fleet.set_offsets(health.clock_offsets())
            body["fleet"] = fleet.snapshot()
        return body

    # ------------------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(
            target=self._background_loop, name="hvd-background", daemon=True
        )
        self._thread.start()
        # Caller spins until initialization completes
        # (ref: operations.cc:662-664).
        self._initialized.wait()
        if self._init_error is not None:
            raise self._init_error
        # Env-driven exporters (HOROVOD_METRICS_PORT / _FILE): started
        # only after init succeeds so /status always has a live engine
        # behind it. Default-off: no env knobs, no threads, no sockets.
        from ..common import metrics_export

        fleet = self.controller.fleet if self.controller is not None else None
        self._exporters = metrics_export.start_exporters_from_env(
            registry=self.registry, fleet=fleet, status_fn=self.status,
            rank=self.rank,
            trace_fn=(self._trace_json if self.rank == 0 else None),
        )
        # Health plane (docs/health.md): sampler ring + alert engine,
        # default-on with bounded memory (the flight-recorder bar);
        # HOROVOD_METRICS_HISTORY_SAMPLES=0 or _SAMPLE_SECONDS=0 turns
        # it off entirely — no thread, no ring, no rules.
        if env_cfg.health_plane_enabled():
            from ..common import alerts as alerts_mod
            from ..common import timeseries as ts_mod

            self.sampler = ts_mod.MetricsSampler(self.registry)
            self.alerts = alerts_mod.AlertEngine(
                self.sampler.store, self.registry, tracer=self.tracer,
                stale_after=3 * max(self.sampler.interval, 1.0))
            self.sampler.add_tick_callback(self.alerts.evaluate)
            ctrl = self.controller
            if ctrl is not None:
                # Per-rank alert state rides the telemetry piggyback;
                # rank 0 folds it so /alerts names the offending rank
                # fleet-wide (the liveness-verdict attribution bar).
                ctrl.alert_push = self.alerts.push_state
                if ctrl.is_coordinator:
                    self._fleet_alerts = alerts_mod.FleetAlerts(self.size)
                    ctrl.alert_sink = self._fleet_alerts
                    # Mirror the fleet verdicts to the rendezvous KV
                    # (``alerts/fleet``) each sampler tick: the driver-
                    # side elasticity controller reads firing_by_rule
                    # there to name straggler ranks worth draining out
                    # (runner/elastic/controller.py). Best-effort — a
                    # down KV must never stall the sampler.
                    from ..common.drain import _kv_from_env

                    kv = _kv_from_env()
                    if kv is not None:
                        import json as _json

                        fleet = self._fleet_alerts
                        inflight = {"busy": False}

                        def _mirror_alerts(_store, _kv=kv, _fleet=fleet):
                            # Ship off-thread, never overlapping: a put
                            # into a down KV retries with backoff, and
                            # that wait belongs to a throwaway daemon
                            # thread, not the sampler tick.
                            if inflight["busy"]:
                                return
                            inflight["busy"] = True
                            snap = _fleet.snapshot()

                            def _send():
                                try:
                                    _kv.put("alerts", "fleet", _json.dumps(
                                        {"wall": time.time(),
                                         "firing_by_rule":
                                             snap["firing_by_rule"]},
                                        separators=(",", ":")).encode())
                                except Exception:
                                    pass
                                finally:
                                    inflight["busy"] = False

                            threading.Thread(target=_send, daemon=True,
                                             name="hvd-alerts-kv").start()

                        self.sampler.add_tick_callback(_mirror_alerts)
            self.sampler.start()
            for exp in self._exporters:
                if isinstance(exp, metrics_export.MetricsHTTPServer):
                    exp.add_view("timeseries", self._timeseries_view)
                    exp.add_view("alerts", self._alerts_view)
        # Goodput plane: the efficiency ledger rides the same endpoint
        # (independent of the health plane — the ledger has no sampler
        # thread to disable).
        for exp in self._exporters:
            if isinstance(exp, metrics_export.MetricsHTTPServer):
                exp.add_view("goodput", self._goodput_view)
        # Events plane (docs/events.md): lifecycle batches ride the
        # telemetry piggyback exactly like spans and alert state; rank 0
        # folds them into the causally-ordered /events chronicle.
        from ..common import events as events_mod

        ev_rec = events_mod.current(rank=self.rank)
        events_mod.set_rank(self.rank)
        if ev_rec.enabled:
            ctrl = self.controller
            if ctrl is not None:
                ctrl.events_push = ev_rec.make_push()
                if ctrl.is_coordinator:
                    self._fleet_events = events_mod.FleetEvents(self.size)
                    ctrl.events_sink = self._fleet_events
            for exp in self._exporters:
                if isinstance(exp, metrics_export.MetricsHTTPServer):
                    exp.add_view("events", self._events_view)
            events_mod.emit(events_mod.ENGINE_INIT, rank=self.rank,
                            size=self.size)
            # Journal the native-core verdict once per engine: which
            # data plane this rank actually runs (docs/native.md).
            from ..cc import native as native_mod

            nst = native_mod.status()
            if nst["loaded"]:
                events_mod.emit(events_mod.NATIVE_LOADED, rank=self.rank,
                                abi=nst["abi"], threads=nst["threads"])
            else:
                events_mod.emit(
                    events_mod.NATIVE_FALLBACK, rank=self.rank,
                    built=nst["built"], disabled=nst["disabled"])

    def _background_loop(self):
        try:
            if self._explicit_backend is not None:
                self.backend = self._explicit_backend
            elif self.size == 1:
                from ..backend.local import LocalBackend

                self.backend = LocalBackend()
            else:
                from ..backend.tcp import TcpBackend

                self.backend = TcpBackend(self.rank, self.size,
                                          scope=self._scope,
                                          registry=self.registry)
            self.backend.set_topology(self.local_rank, self.local_size,
                                      self.cross_rank, self.cross_size)
            # Backend phase spans (ring/star/TCP sender dwell) land in
            # this engine's flight recorder.
            self.backend.tracer = self.tracer
            self.controller = Controller(self.backend, self.size, self.rank,
                                         timeline=self.timeline,
                                         registry=self.registry,
                                         tracer=self.tracer)
            from .parameter_manager import ParameterManager

            self.param_manager = ParameterManager(
                is_coordinator=(self.rank == 0),
                registry=self.registry,
            )
        except BaseException as e:  # surface rendezvous failures to init()
            self._init_error = e
            self._initialized.set()
            return
        self._initialized.set()
        try:
            # Hierarchical allreduce requires every rank to take the
            # same data-plane path, so validity (homogeneous contiguous
            # host packing) is agreed collectively — a single bitwise
            # AND word, like the reference's is_homogeneous check at
            # controller init (mpi_controller.cc:26-82). Runs after
            # _initialized so start() stays non-collective; every rank's
            # background thread performs it before its first cycle.
            self._hier_valid = False
            if self.size > 1:
                from ..backend.ring import hierarchical_capable

                # Bit 0: hierarchical topology valid; bit 1: this rank
                # votes for the leader-based cross schedule (all its
                # local peers reachable over a live shm overlay). Both
                # AND-agreed in one word so every rank lands on the
                # same schedule — HOROVOD_HIERARCHICAL_MODE=auto
                # resolves through leader_hier_ok.
                # Bit 2: this rank's local group is covered by a live
                # per-HOST shm arena (the leader schedule's arena
                # legs) — AND-agreed like the rest, so a host that
                # cannot map its arena degrades every host to the
                # per-pair rings consistently.
                word = 0
                if hierarchical_capable(self.backend):
                    word |= 1
                if self.backend.prefers_leader_hierarchy():
                    word |= 2
                if self.backend.prefers_arena_hierarchy():
                    word |= 4
                agreed = self.backend.allreduce_words([word], "and")[0]
                self._hier_valid = bool(agreed & 1)
                self.backend.leader_hier_ok = bool(agreed & 1) and bool(
                    agreed & 2)
                self.backend.arena_hier_ok = bool(agreed & 1) and bool(
                    agreed & 4)
            # Static toggle (ref: HOROVOD_HIERARCHICAL_ALLREDUCE,
            # operations.cc:468-478; =auto engages exactly when the
            # agreed topology is hierarchical — co-located ranks on
            # more than one host); autotune may flip it later at
            # parameter-sync boundaries.
            self.backend.hierarchical = self._hier_valid and (
                env_cfg.hierarchical_allreduce_setting() != "off"
            )
            self.backend.hier_allgather = (
                self._hier_valid
                and env_cfg.get_bool(env_cfg.HIERARCHICAL_ALLGATHER, False)
            )
            # Arms rebuild happens before the first cycle, hence before
            # any sample window can open.
            self.param_manager.set_tune_hierarchical(self._hier_valid)
            # Ordered op registry; first Enabled() implementation wins
            # (ref: CreateOperationManager, operations.cc:142-249).
            from .operation_manager import build_default

            self.op_manager = build_default(self.backend)
            # Channel executors for the locally configured width; any
            # higher channel id the coordinator assigns is created
            # lazily at first dispatch.
            for ch in range(env_cfg.num_channels()):
                self._executor_for(ch)
            # Liveness plane: heartbeats + failure detector over the
            # mesh sockets (no-op for local/threaded backends or when
            # HOROVOD_HEARTBEAT_INTERVAL_SECONDS/_MISS_LIMIT is 0).
            from ..common import health

            self._health = health.maybe_start_monitor(self)
            while self._run_loop_once():
                pass
        except HorovodInternalError as e:
            # Transport death (peer gone, socket timeout), liveness
            # verdict, or injected fault: the mesh is unusable, so
            # EVERY pending handle — and every enqueue from here on —
            # fails with the FIRST cause (the latched error: a liveness
            # verdict or an executor's transport death wins over the
            # follow-on error that killed the loop), unblocking all
            # framework threads into elastic recovery at once (ref: the
            # reference's ShutDown → callbacks-with-status path,
            # operations.cc:300-330).
            self._latch_fatal(e)
            first = self._fatal_error or e
            logger.error("background loop failed: %s", first)
            self.tensor_queue.finalize(Status.Aborted(str(first)))
        except BaseException as e:
            logger.error("background loop failed: %s", e)
            self.tensor_queue.finalize(Status.UnknownError(str(e)))
        finally:
            # Stop order matters: stop the liveness monitor (it must not
            # read our own teardown as a peer death), queue the stop
            # sentinels, then shut the backend (severing sockets
            # unblocks any executor parked in a recv — its op fails
            # with TransportError and its entries are finished by the
            # executor's own error path), then join.
            if self._health is not None:
                self._health.stop()
            # Black-box stitching (rank 0): the per-rank flight dumps
            # were written at latch time; merge whatever landed in
            # HOROVOD_TRACE_DIR with the health verdict into one
            # post-mortem before the process winds down.
            if self.rank == 0 and self._fatal_error is not None:
                try:
                    self._stitch_post_mortem()
                except Exception:  # pragma: no cover - best-effort
                    logger.exception("post-mortem stitch failed")
            for ex in list(self._executors.values()):
                ex.queue.put(_EXEC_STOP)
            if self.backend is not None:
                self.backend.shutdown()
            for ex in list(self._executors.values()):
                ex.thread.join(timeout=10)
                ex.gauge.clear_function(ex.depth)
                if ex.thread.is_alive():  # pragma: no cover - wedged op
                    logger.warning(
                        "channel %d executor did not exit cleanly",
                        ex.channel)
            self.timeline.shutdown()

    # ------------------------------------------------------------------
    # pipelined-execution plumbing
    def _executor_for(self, channel: int) -> _ChannelExecutor:
        ex = self._executors.get(channel)
        if ex is None:
            ex = self._executors[channel] = _ChannelExecutor(self, channel)
        return ex

    def _latch_fatal(self, exc: HorovodInternalError):
        first = False
        with self._inflight_cond:
            if self._fatal_error is None:
                self._fatal_error = exc
                first = True
            self._inflight_cond.notify_all()
        self._wake.set()
        if first:
            # Auto-dump the flight recorder the moment the FIRST cause
            # latches (docs/tracing.md): the ring still holds the
            # events leading up to the failure, and the dying loop's
            # teardown (rank 0) stitches every rank's dump into the
            # post-mortem. Outside the condvar — this writes a file.
            try:
                self._dump_post_mortem(exc)
            except Exception:  # pragma: no cover - best-effort
                logger.exception("flight-recorder dump failed")

    def _check_fatal(self):
        if self._fatal_error is not None:
            raise self._fatal_error

    def _response_done(self):
        with self._inflight_cond:
            self._inflight -= 1
            self._inflight_cond.notify_all()

    def _dispatch(self, resp: Response):
        """Hand a response to its channel executor, blocking while the
        in-flight window is full (backpressure: negotiation must not
        race arbitrarily far ahead of execution)."""
        ex = self._executor_for(resp.channel)
        with self._inflight_cond:
            while (self._inflight >= self._max_inflight
                   and self._fatal_error is None):
                self._inflight_cond.wait(0.1)
            # On a fatal error the window opens unconditionally: the
            # executor discards the response and the dying loop's
            # finalize fails its entries, so accounting stays straight.
            self._inflight += 1
        resp._dispatch_ns = clock.mono_ns()  # executor queue-wait span
        ex.queue.put(resp)

    def _drain_channels(self):
        """Fence: wait until every dispatched response on every channel
        has finished (or the engine died trying)."""
        with self._inflight_cond:
            while self._inflight > 0 and self._fatal_error is None:
                self._inflight_cond.wait(0.1)
        self._check_fatal()

    def _cycle_wait(self) -> str:
        """Coalescing wait before a cycle; returns the wake reason."""
        if self._shutdown_requested.is_set():
            return "shutdown"
        if self.cycle_time_s <= 0:
            return "spin"
        if not self._event_cycles:
            # Fixed-sleep baseline (HOROVOD_CYCLE_EVENT_DRIVEN=0): the
            # pre-pipelining schedule, kept for A/B latency measurement.
            time.sleep(self.cycle_time_s)
            return "timeout"
        woke = self._wake.wait(self.cycle_time_s)
        # Clear BEFORE popping messages: an enqueue landing after the
        # pop re-sets it, so the next cycle wakes immediately; one
        # landing in between is popped now and costs one spurious wake.
        self._wake.clear()
        return "enqueue" if woke else "timeout"

    # ------------------------------------------------------------------
    def _run_loop_once(self) -> bool:
        """(ref: RunLoopOnce, operations.cc:566-616)"""
        reason = self._cycle_wait()
        self._m_wake[reason].inc()
        self._check_fatal()
        cycle_t0 = clock.monotonic()
        self.timeline.mark_cycle()
        messages = self.tensor_queue.pop_messages_from_queue()
        want_shutdown = self._shutdown_requested.is_set()
        resp_list, should_shutdown = self.controller.compute_response_list(
            messages, shutdown=want_shutdown
        )
        # Terminal abort verdict: a tensor-less ERROR + shutdown is a
        # stall abort or a liveness death declaration ("rank 2 (host X)
        # declared dead..."). Do NOT drain channels first — an executor
        # may be parked in a recv the dead rank will never feed (with
        # HOROVOD_TCP_TIMEOUT_SECONDS=0, forever). Latch the verdict as
        # first cause and die; the teardown path severs every socket,
        # which unblocks parked executors, and finalize fails every
        # pending handle with the attributed reason.
        if should_shutdown:
            for resp in resp_list.responses:
                if (resp.response_type == ResponseType.ERROR
                        and not resp.tensor_names and resp.error_message):
                    exc = HorovodInternalError(resp.error_message)
                    self._latch_fatal(exc)
                    raise exc
        if resp_list.responses:
            self.response_cycles += 1
        # Autotune (ref: operations.cc:592-600): windows are counted in
        # response cycles, identical on all ranks, so the parameter-sync
        # broadcast below lines up as a collective. It runs BEFORE this
        # cycle's completion callbacks fire so that when a caller is
        # unblocked by any handle completed in this cycle, the tuner
        # state (notably `done`) is already identical on every rank —
        # otherwise two ranks polling `done` after each op can observe
        # the flip one op apart and desync their enqueue streams.
        if (self.param_manager is not None and not self.param_manager.done
                and resp_list.responses):
            nbytes = sum(
                self.controller._sizes_by_name.get(n, 0)
                for resp in resp_list.responses
                for n in resp.tensor_names
            )
            if self.param_manager.update(nbytes):
                # Parameter-sync fence: every rank reaches this point at
                # the same response-cycle count and drains its channels
                # before the sync, so categorical toggles (hierarchical,
                # cache) can never flip under an op still in flight on
                # one rank but not another — that divergence would pick
                # mismatched data-plane algorithms and deadlock.
                self._drain_channels()
                payload = self.controller.synchronize_parameters(
                    self.param_manager.serialize()
                )
                if not self.controller.is_coordinator:
                    self.param_manager.apply(payload)
                self.controller.fusion_threshold = (
                    self.param_manager.fusion_threshold
                )
                self.cycle_time_s = self.param_manager.cycle_time_ms / 1000.0
                # Categorical toggles land collectively at the same
                # boundary on every rank (ref: parameter_manager.h:163-228
                # hierarchical/cache CategoricalParameterChains).
                self.controller.cache_enabled = self.param_manager.cache_enabled
                self.backend.hierarchical = (
                    self._hier_valid and self.param_manager.hierarchical
                )
        for resp in resp_list.responses:
            if resp.response_type in _FENCE_TYPES:
                # Fences preserve program order relative to the response
                # stream: everything dispatched before them finishes
                # first, and they run inline so nothing overlaps them.
                self._drain_channels()
                self._perform_operation(resp)
            else:
                self._dispatch(resp)
        # Cycle work duration (waits excluded) + liveness stamp: the
        # last-cycle age gauge is how /status distinguishes "idle" from
        # "background loop wedged".
        self._last_cycle_ts = clock.monotonic()
        self._m_cycle.observe(self._last_cycle_ts - cycle_t0)
        if should_shutdown:
            # Clean shutdown (every rank agreed): a fence — in-flight
            # collectives complete before pending handles are finalized.
            # Abort verdicts (stall / liveness) took the hard latch+
            # raise path above and never reach here.
            self._drain_channels()
            self.tensor_queue.finalize(
                Status.Aborted("Horovod has been shut down."))
            return False
        return True

    # ------------------------------------------------------------------
    def _perform_operation(self, resp: Response):
        """(ref: PerformOperation, operations.cc:253-330). Runs on a
        channel executor thread for non-fence responses — inside the
        response's channel scope, so every data-plane frame it moves is
        tagged with the channel and demultiplexes cleanly from
        concurrent collectives — and inline on the background thread
        for fences (control-plane tagged). The whole operation runs
        inside the response's trace scope, so every backend span it
        produces (ring segments, star phases, sender dwell) carries
        the wire-assigned trace id."""
        scope = getattr(self.backend, "channel_scope", None)
        with tracing.trace_scope(resp.trace_id), self.tracer.span(
                f"exec.{resp.response_type.name.lower()}",
                cat=tracing.CAT_EXEC,
                args={"channel": resp.channel,
                      "tensors": len(resp.tensor_names)}):
            if scope is None or resp.response_type in _FENCE_TYPES:
                return self._execute_response(resp)
            with scope(resp.channel):
                return self._execute_response(resp)

    def _execute_response(self, resp: Response):
        entries = self.tensor_queue.get_tensor_entries(resp.tensor_names)
        if entries and self.tracer.enabled:
            # Queue-dwell span: earliest enqueue of this response's
            # tensors → execution start (inherits the trace scope set
            # by _perform_operation).
            now = clock.mono_ns()
            t0 = min((e.enqueued_ns for e in entries if e.enqueued_ns),
                     default=0)
            if t0:
                self.tracer.emit("queue.dwell", tracing.CAT_QUEUE, t0,
                                 now - t0, args={"tensors": len(entries)})
        if resp.response_type != ResponseType.ERROR:
            self._record_response(
                resp.response_type, len(entries),
                sum(e.tensor.nbytes for e in entries if e.tensor is not None),
            )
        for e in entries:
            # Top-level op phase opens when execution begins
            # (ref: Timeline::Start, timeline.h:106-110); activities
            # nest inside; _finish closes it.
            self.timeline.start(e.tensor_name, resp.response_type.name)
        try:
            if resp.response_type == ResponseType.ERROR:
                for e in entries:
                    self._finish(e, Status.PreconditionError(resp.error_message), None)
                return
            if resp.response_type in (ResponseType.ALLREDUCE, ResponseType.ADASUM):
                self._do_allreduce(resp, entries)
            elif resp.response_type == ResponseType.ALLGATHER:
                for e in entries:
                    # Negotiated total output bytes — identical on every
                    # rank, so the ring/star pick is consistent.
                    row = (int(np.prod(e.tensor.shape[1:]))
                           if e.tensor.ndim else 1)
                    nbytes = (sum(resp.tensor_sizes) * row
                              * e.tensor.dtype.itemsize)
                    op = self.op_manager.select(ResponseType.ALLGATHER,
                                                nbytes=nbytes,
                                                ndim=e.tensor.ndim)
                    t0 = clock.monotonic()
                    with self.timeline.activity(e.tensor_name, op.name):
                        out = op.execute(e.tensor, list(resp.tensor_sizes))
                    self._observe_op(op.name, clock.monotonic() - t0)
                    self._finish(e, Status.OK(), out)
            elif resp.response_type == ResponseType.BROADCAST:
                op = self.op_manager.select(ResponseType.BROADCAST)
                for e in entries:
                    arr = e.tensor if self.rank == e.root_rank else None
                    t0 = clock.monotonic()
                    with self.timeline.activity(e.tensor_name, op.name):
                        out = op.execute(arr, e.root_rank)
                    self._observe_op(op.name, clock.monotonic() - t0)
                    self._finish(e, Status.OK(), out)
            elif resp.response_type == ResponseType.ALLTOALL:
                op = self.op_manager.select(ResponseType.ALLTOALL)
                for e in entries:
                    t0 = clock.monotonic()
                    with self.timeline.activity(e.tensor_name, op.name):
                        out, recv_splits = op.execute(e.tensor, e.splits)
                    self._observe_op(op.name, clock.monotonic() - t0)
                    e.output = out
                    self._finish(e, Status.OK(), (out, recv_splits))
            elif resp.response_type == ResponseType.BARRIER:
                self.backend.barrier()
                for e in entries:
                    self._finish(e, Status.OK(), None)
            elif resp.response_type == ResponseType.JOIN:
                # All ranks joined; complete this rank's pending join
                # entry (the JOIN response carries no tensor names).
                for e in entries + self.tensor_queue.pop_entries_by_prefix("join."):
                    self._finish(e, Status.OK(), np.asarray(resp.last_joined_rank))
            else:
                for e in entries:
                    self._finish(
                        e, Status.UnknownError(f"bad response {resp.response_type}"), None
                    )
        except HorovodInternalError as exc:
            # Transport failure mid-collective: stamp the collective
            # phase on the error ("... (during allreduce)" — the
            # attribution the liveness plane threads through the whole
            # stack), fail the in-flight entries with the FIRST cause
            # when one is already latched (a liveness verdict beats the
            # socket noise its sever produced), then re-raise so the
            # background loop dies and finalizes every OTHER pending
            # handle too — a broken mesh can't serve the next response
            # either, and leaving those handles parked would hang their
            # waiters.
            if isinstance(exc, TransportError) and exc.phase is None:
                exc.phase = resp.response_type.name.lower()
            first = self._fatal_error
            status = Status.Aborted(str(first if first is not None else exc))
            for e in entries:
                self._finish(e, status, None)
            raise
        except Exception as exc:
            for e in entries:
                self._finish(e, Status.UnknownError(str(exc)), None)

    def _do_allreduce(self, resp: Response, entries: List[TensorTableEntry]):
        adasum = resp.response_type == ResponseType.ADASUM
        pre, post = resp.prescale_factor, resp.postscale_factor
        if not entries:
            # This rank joined: contribute zeros of the full negotiated
            # shape (ref: JoinOp semantics, controller.cc:220-231). Full
            # shape — not empty — so ring and star ranks see identical
            # element counts and take the same data-plane path; zeros
            # are the identity for the SUM join supports.
            if self.size > 1:
                from ..backend.base import wire_codec_scope
                from ..common.types import from_wire_dtype

                count = 0
                for shp in resp.tensor_shapes:
                    c = 1
                    for d in shp:
                        c *= d
                    count += c
                zeros = np.zeros(
                    count, from_wire_dtype(resp.tensor_type)
                )
                # Same registry selection as contributing ranks: the
                # negotiated byte count is identical, so the joined rank
                # lands on the same data-plane algorithm. Same codec
                # scope too — a joined rank shipping full-width frames
                # into a compressed collective would desync the stream
                # (zeros are exactly representable in every codec, so
                # no error-feedback state is needed here).
                rop = ReduceOp(resp.reduce_op or int(ReduceOp.SUM))
                codec = self._wire_codec_for(resp, zeros.dtype)
                op = self.op_manager.select(
                    ResponseType.ADASUM if adasum else ResponseType.ALLREDUCE,
                    nbytes=zeros.nbytes, reduce_op=rop,
                )
                with wire_codec_scope(codec, self._comp_stats):
                    op.execute(zeros, rop, owned=True)
            return
        name0 = entries[0].tensor_name
        # `owned` tracks whether buf is a fresh engine-side temporary
        # (packed by the native fusion memcpy or allocated by prescale):
        # the ring data plane may then reduce it in place instead of
        # taking a defensive copy. A user-enqueued tensor (single
        # unfused entry) and the persistent pure-python fusion storage
        # (reused next cycle, while results may still alias it) are NOT
        # owned.
        if len(entries) == 1:
            buf = entries[0].tensor
            owned = False
            shapes = None
        else:
            # Fusion buffer: flatten + concat (ref: MemcpyInFusionBuffer,
            # collective_operations.cc; native multithreaded memcpy when
            # the C++ core is built).
            with self.timeline.activity(name0, MEMCPY_IN_FUSION_BUFFER):
                shapes = [e.tensor.shape for e in entries]
                buf, owned = self._pack_fusion(entries, resp.channel)
        if pre != 1.0:
            buf = _scale_np(buf, pre)
            owned = True
        buf = np.asarray(buf)
        rop = ReduceOp(resp.reduce_op or int(ReduceOp.SUM))
        # Wire compression: apply the error-feedback residual and
        # project the contribution onto the codec grid BEFORE the
        # collective, then run the data plane inside the codec scope so
        # ring segments / star frames / arena deposits ship encoded
        # bytes (docs/running.md "Wire compression").
        codec = self._wire_codec_for(resp, buf.dtype)
        first_hop = None
        if codec is not None:
            buf, first_hop = self._apply_error_feedback(
                codec, resp, buf, owned)
            owned = True
        # First Enabled() implementation wins; the winning op's name is
        # the timeline activity, like the reference's NCCL_ALLREDUCE /
        # MPI_ALLREDUCE lanes (common.h:32-62).
        op = self.op_manager.select(
            ResponseType.ADASUM if adasum else ResponseType.ALLREDUCE,
            nbytes=buf.nbytes, reduce_op=rop,
        )
        from ..backend.base import wire_codec_scope

        t0 = clock.monotonic()
        with self.timeline.activity(name0, op.name), \
                wire_codec_scope(codec, self._comp_stats,
                                 first_hop=first_hop):
            red = op.execute(buf, rop, owned=owned)
        self._observe_op(op.name, clock.monotonic() - t0)
        if post != 1.0:
            red = _scale_np(red, post)
        if shapes is None:
            self._finish(entries[0], Status.OK(), red.reshape(entries[0].tensor.shape))
        else:
            with self.timeline.activity(name0, MEMCPY_OUT_FUSION_BUFFER):
                off = 0
                for e, shape in zip(entries, shapes):
                    n = int(np.prod(shape)) if shape else 1
                    self._finish(e, Status.OK(),
                                 red[off : off + n].reshape(shape))
                    off += n

    # -- wire compression (docs/running.md "Wire compression") ---------
    def _wire_codec_for(self, resp: Response, dtype):
        """Resolve the response's wire-carried codec id. The id was
        assigned by the coordinator from NEGOTIATED inputs, so every
        rank resolves the same codec for the same response — the
        applicability re-check here (fp32, multi-rank) is pure
        defense: both inputs are themselves negotiated, so it can
        never diverge across ranks."""
        if not resp.codec or self.size <= 1:
            return None
        codec = compression.codec_by_id(resp.codec)
        if codec is None or not codec.applicable(dtype):
            return None
        return codec

    def _apply_error_feedback(self, codec, resp: Response,
                              buf: np.ndarray, owned: bool):
        """Error feedback (Seide et al. 2014; Karimireddy et al. 2019):
        add the residual left over from this tensor's previous
        compressed round, project the sum onto the codec grid
        (decode∘encode — what the wire will actually carry), and stash
        the new residual = pre-encode value minus decoded wire value.
        Returns ``(wire, enc)``: the grid-projected buffer (always
        engine-owned) AND the encoded bytes the projection ran through.

        Running the projection HERE, once per tensor, buys two things:
        the residual definition from the issue holds exactly, and every
        rank's contribution entering the collective is bitwise the
        value its peers will decode — the rank-consistency the
        uncompressed planes get for free. The encoded bytes ride the
        codec scope as the op's FIRST-HOP payload (zero-redundancy
        first hop): the first ring/star/arena hop ships them directly
        instead of re-encoding the identical values, so this encode —
        observed as phase="encode", the wire-truth ledger — is the only
        cast pass the first hop ever pays (the residual bookkeeping
        alone stays under phase="feedback")."""
        flat = np.ascontiguousarray(buf).reshape(-1)
        key = "|".join(resp.tensor_names)
        t0 = clock.monotonic()
        residual = self._error_feedback.get(key, flat.size)
        if residual is not None:
            if owned:
                # flat aliases the engine-owned buf: add in place.
                np.add(flat, residual, out=flat)
                pre = flat
            else:
                pre = flat + residual
        else:
            pre = flat
        t_enc = clock.monotonic()
        enc = codec.encode(pre)
        enc_s = clock.monotonic() - t_enc
        wire = codec.decode(enc, pre.size)
        self._error_feedback.update(key, pre, wire)
        self._comp_stats.observe("encode", enc_s)
        self._comp_stats.observe("feedback",
                                 clock.monotonic() - t0 - enc_s)
        return wire.reshape(buf.shape), enc

    def _pack_fusion(
        self, entries: List[TensorTableEntry], channel: int = 0
    ) -> Tuple[np.ndarray, bool]:
        """Copy entries into a fusion buffer; returns (buf, owned).
        The native threaded memcpy packs into a FRESH buffer every
        cycle (owned=True: the data plane may reduce it in place and
        results may alias it); the pure-python fallback packs into the
        persistent per-(channel, dtype) storage reused across cycles
        (owned=False — in-place reduction there would let next cycle's
        pack corrupt results still aliased by callers). Keyed by channel
        because executors pack concurrently; within a channel execution
        is serial, so the reuse stays race-free."""
        from ..cc import native

        dtype = entries[0].tensor.dtype
        total = sum(int(e.tensor.size) for e in entries)
        packed = native.pack([e.tensor for e in entries])
        if packed is not None:
            return packed.view(dtype)[:total], True
        key = (channel, dtype.str)
        storage = self._fusion_storage.get(key)
        if storage is None or storage.size < total:
            storage = np.empty(max(total, 1), dtype)
            self._fusion_storage[key] = storage
        off = 0
        for e in entries:
            n = int(e.tensor.size)
            storage[off : off + n] = np.ravel(e.tensor)
            off += n
        return storage[:total], False

    def _finish(self, entry: TensorTableEntry, status: Status, result):
        self.timeline.end(entry.tensor_name, entry.tensor_name.split(".")[0])
        if entry.callback is not None:
            entry.callback(status, result)

    # ------------------------------------------------------------------
    # Enqueue API (ref: EnqueueTensor*, operations.cc:840-1068)
    def _auto_name(self, op: str, name: Optional[str]) -> str:
        if name is not None:
            return f"{op}.{name}"
        with self._counter_lock:
            c = self._op_counter.get(op, 0)
            self._op_counter[op] = c + 1
        return f"{op}.noname.{c}"

    def _enqueue(
        self,
        req_type: RequestType,
        arr: Optional[np.ndarray],
        name: str,
        root_rank: int = 0,
        prescale: float = 1.0,
        postscale: float = 1.0,
        splits: Optional[List[int]] = None,
        reduce_op: ReduceOp = ReduceOp.SUM,
    ) -> int:
        handle = self.handles.allocate()
        req = Request(
            request_rank=self.rank,
            request_type=req_type,
            tensor_type=to_wire_dtype(arr.dtype) if arr is not None else 0,
            tensor_name=name,
            root_rank=root_rank,
            device=-1,
            tensor_shape=tuple(arr.shape) if arr is not None else (),
            prescale_factor=prescale,
            postscale_factor=postscale,
            reduce_op=int(reduce_op),
        )
        if arr is not None and self.controller is not None:
            self.controller.record_tensor_size(name, arr.nbytes)

        def callback(status: Status, result):
            self.handles.mark_done(handle, status, result)

        entry = TensorTableEntry(
            tensor_name=name,
            tensor=arr,
            root_rank=root_rank,
            callback=callback,
            splits=splits,
            enqueued_ns=clock.mono_ns(),
        )
        status = self.tensor_queue.add_to_tensor_queue(entry, req)
        if not status.ok():
            self.handles.mark_done(handle, status, None)
        return handle

    def enqueue_allreduce(
        self,
        arr: np.ndarray,
        name: Optional[str] = None,
        op: ReduceOp = ReduceOp.SUM,
        prescale: float = 1.0,
        postscale: float = 1.0,
    ) -> int:
        # AVERAGE lowers to SUM + postscale 1/size
        # (ref: operations.cc:851-858).
        if op == ReduceOp.AVERAGE:
            postscale = postscale / self.size
            op = ReduceOp.SUM
        rt = RequestType.ADASUM if op == ReduceOp.ADASUM else RequestType.ALLREDUCE
        if op == ReduceOp.ADASUM and self.size & (self.size - 1):
            raise ValueError("Adasum requires a power-of-2 number of ranks")
        reduce_op = op if op in (
            ReduceOp.MIN, ReduceOp.MAX, ReduceOp.PRODUCT
        ) else ReduceOp.SUM
        return self._enqueue(
            rt, np.asarray(arr), self._auto_name("allreduce", name), 0,
            prescale, postscale, reduce_op=reduce_op,
        )

    def enqueue_allgather(self, arr: np.ndarray, name: Optional[str] = None) -> int:
        return self._enqueue(
            RequestType.ALLGATHER, np.asarray(arr), self._auto_name("allgather", name)
        )

    def enqueue_broadcast(
        self, arr: np.ndarray, root_rank: int, name: Optional[str] = None
    ) -> int:
        return self._enqueue(
            RequestType.BROADCAST,
            np.asarray(arr),
            self._auto_name("broadcast", name),
            root_rank,
        )

    def enqueue_alltoall(
        self, arr: np.ndarray, splits: Optional[List[int]], name: Optional[str] = None
    ) -> int:
        arr = np.asarray(arr)
        if splits is None:
            if arr.shape[0] % self.size:
                raise ValueError("tensor dim 0 must be divisible by size when splits=None")
            splits = [arr.shape[0] // self.size] * self.size
        if sum(splits) != arr.shape[0]:
            raise ValueError("splits must sum to tensor dim 0")
        return self._enqueue(
            RequestType.ALLTOALL,
            arr,
            self._auto_name("alltoall", name),
            splits=list(splits),
        )

    def enqueue_join(self) -> int:
        return self._enqueue(RequestType.JOIN, None, self._auto_name("join", None))

    def enqueue_barrier(self) -> int:
        return self._enqueue(
            RequestType.BARRIER,
            np.zeros(0, np.uint8),
            self._auto_name("barrier", None),
        )

    # ------------------------------------------------------------------
    # tracing plane (docs/tracing.md)
    def render_trace(self) -> dict:
        """Merged Chrome/Perfetto document: one process lane per rank.
        On the coordinator this folds every rank's collected span
        batches (clock-aligned via the health plane's RTT offsets, or
        wall anchors as the fallback); elsewhere it renders this rank's
        own flight recorder."""
        ctrl = self.controller
        offsets = {}
        health = self._health
        if health is not None:
            offsets = health.clock_offsets()
        if ctrl is not None and ctrl.trace_collector is not None:
            ctrl.collect_local()
            segments = ctrl.trace_collector.segments(
                offsets, clock.anchor_meta())
        else:
            segments = [{"rank": self.rank,
                         "events": self.tracer.recorder.snapshot(),
                         "anchor": clock.anchor_meta(), "offset_ns": 0}]
        doc = tracing.render_chrome(
            segments, base_ns=clock.MONO_ANCHOR_NS,
            metadata={"horovod_trace": {
                "rank": self.rank, "size": self.size,
                "clock_offsets_ns": {str(k): v for k, v in offsets.items()},
            }})
        self._append_lifecycle_instants(doc, offsets)
        return doc

    def _append_lifecycle_instants(self, doc: dict, offsets: dict):
        """Land the lifecycle chronicle (docs/events.md) as instant
        events in the merged trace: every re-mesh, drain, commit and
        swap shows as a vertical marker inline with the spans that
        surround it. Coordinator uses the fleet fold (all ranks,
        skew-adjusted); elsewhere the local ring."""
        from ..common import events as events_mod
        from ..utils import chrome_trace

        base = clock.MONO_ANCHOR_NS
        fleet = self._fleet_events
        if fleet is not None:
            rows = [(d["rank"], d) for d in fleet.merged()]
        else:
            rec = events_mod.active()
            if rec is None or not rec.enabled:
                return
            rows = [(d["rank"], d)
                    for d in (events_mod.to_dict(e)
                              for e in rec.snapshot())]
        for r, d in rows:
            try:
                ts_us = (int(d["mono_ns"]) - offsets.get(r, 0) - base) / 1e3
            except (KeyError, TypeError, ValueError):
                continue
            doc["traceEvents"].append(chrome_trace.instant(
                str(d.get("kind", "event")), ts_us, pid=r,
                cat="lifecycle",
                args={k: v for k, v in d.items() if k != "mono_ns"}))

    def _trace_json(self) -> str:
        import json

        return json.dumps(self.render_trace())

    def _write_trace_file(self):
        """HOROVOD_TRACE_FILE dump at shutdown: rank 0 writes the
        merged trace; with `{rank}` in the path every rank writes its
        own lane (useful without a coordinator to pull through)."""
        path = env_cfg.trace_file()
        if not path or not self.tracer.enabled:
            return
        if self.rank != 0 and "{rank}" not in path:
            return
        try:
            doc = self.render_trace()
            from ..utils import chrome_trace

            out = path.replace("{rank}", str(self.rank))
            chrome_trace.write_trace(
                out, doc.pop("traceEvents"), metadata=doc)
            self.tracer.last_dump = out
            logger.info("merged trace written to %s", out)
        except Exception:  # pragma: no cover - best-effort on teardown
            logger.exception("trace file dump failed")

    def _dump_post_mortem(self, exc: BaseException):
        """Every rank's black box: on the first latched fatal error,
        write the flight recorder (last HOROVOD_TRACE_BUFFER_EVENTS
        events, clock anchor, health view, the attributed reason) to
        HOROVOD_TRACE_DIR. No-op without a trace dir."""
        trace_dir = env_cfg.trace_dir()
        if (not trace_dir or not self.tracer.enabled
                or not env_cfg.trace_dump_on_error() or self._pm_dumped):
            return
        self._pm_dumped = True
        os.makedirs(trace_dir, exist_ok=True)
        health = self._health.status() if self._health is not None else None
        extra = {"reason": str(exc), "health": health}
        # Health plane: the last N minutes of every scalar series plus
        # any latched alerts ride the flight dump, so the post-mortem
        # answers "what was trending wrong BEFORE it died", not just
        # "what were the final spans".
        sampler, alert_eng = self.sampler, self.alerts
        if sampler is not None:
            sampler.sample_once()  # capture the dying state too
            extra["timeseries"] = sampler.store.dump_scalars()
        if alert_eng is not None:
            extra["alerts"] = alert_eng.status()
        # Goodput ledger: the post-mortem carries "how much of this job
        # had become training by the time it died" next to the spans.
        extra["goodput"] = self.goodput.view()
        # Lifecycle chronicle (docs/events.md): the ring rides the
        # flight dump so stitch_post_mortem can rebuild the incident
        # sequence (notice -> commit -> drained -> re-mesh -> restore)
        # even when no spool dir was configured.
        from ..common import events as events_mod

        ev_rec = events_mod.active()
        if ev_rec is not None and ev_rec.enabled:
            extra["lifecycle"] = [events_mod.to_dict(e)
                                  for e in ev_rec.snapshot()]
        path = self.tracer.dump_flight(
            tracing.flight_path(trace_dir, self.rank), self.rank,
            extra=extra)
        logger.error("flight recorder dumped to %s", path)

    def _stitch_post_mortem(self):
        """Coordinator: merge every rank's flight dump + the health
        verdict into HOROVOD_TRACE_DIR/postmortem.json (polling briefly
        for ranks still writing theirs)."""
        trace_dir = env_cfg.trace_dir()
        if (not trace_dir or not self.tracer.enabled
                or not env_cfg.trace_dump_on_error()):
            return
        health = self._health.status() if self._health is not None else None
        out = tracing.stitch_post_mortem(
            trace_dir,
            verdict=str(self._fatal_error or ""),
            health=health,
            expect_ranks=self.size,
            offsets=(self._health.clock_offsets()
                     if self._health is not None else None),
        )
        if out:
            logger.error("post-mortem stitched to %s", out)

    # ------------------------------------------------------------------
    def poll(self, handle: int) -> bool:
        return self.handles.poll(handle)

    def synchronize(self, handle: int, timeout: Optional[float] = None):
        return self.handles.wait(handle, timeout)

    def shutdown(self):
        if self._thread is None:
            return
        from ..common import events as events_mod

        events_mod.emit(events_mod.ENGINE_SHUTDOWN, rank=self.rank,
                        size=self.size,
                        reason=str(self._fatal_error or "requested"))
        self._shutdown_requested.set()
        self._wake.set()  # end any coalescing wait immediately
        self._thread.join(timeout=60)
        self._thread = None
        # The recorder is process-wide and outlives this engine across
        # elastic resets — flush the journal writer but keep it alive.
        ev_rec = events_mod.active()
        if ev_rec is not None:
            ev_rec.flush_spool()
        # Goodput ledger: persist a final stamp so the very next
        # lifetime measures downtime from THIS moment, not the last
        # commit (the ledger itself is process-shared and survives).
        self.goodput.stamp(force=True)
        # Health plane down first: a final sample captures shutdown
        # state, then no tick may fire against a dying registry.
        if self.sampler is not None:
            self.sampler.stop()
            self.sampler = None
            self.alerts = None
            self._fleet_alerts = None
        # Trace file AFTER the loop died (the final negotiation rounds'
        # span batches have been collected) but BEFORE exporters stop.
        self._write_trace_file()
        for exp in self._exporters:
            try:
                exp.stop()
            except Exception:  # pragma: no cover - exporter already dead
                pass
        self._exporters = []
        # Detach the pull-gauges' bound methods: on the process-default
        # registry they would otherwise pin this dead Engine (fusion
        # buffers included) for process lifetime and report its frozen
        # state as live after an elastic shutdown+init cycle. Passing
        # OUR callbacks makes the detach conditional — a replacement
        # engine that already re-registered keeps its live callbacks
        # instead of having them silently cleared (the stale-gauge leak:
        # the restarted owner re-registers, the dying one then wipes the
        # registration, and the gauge reports NaN/0 forever).
        for name, fn in self._gauge_fns.items():
            self.registry.gauge(name).clear_function(fn)
