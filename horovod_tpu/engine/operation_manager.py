"""Pluggable collective-op registry with Enabled() priority dispatch.

(ref: horovod/common/ops/operation_manager.{h,cc}:42-122 — per response
type an ordered list of op implementations; the first whose Enabled()
returns true executes. The reference's lists are built at init from
compiled backends, operations.cc:142-249 CreateOperationManager; here
they are built from the process-mode backend's capabilities —
hierarchical ring / flat ring / star — plus Adasum. The TPU traced
plane (ops/traced.py) bypasses this entirely: under jit XLA is the
operation manager.)

The eligibility predicates live in backend/ring.py and are shared with
the backend mixin's own dispatch, so engine-level selection and direct
backend calls can never disagree — disagreement between ranks would
deadlock a collective.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..common.message import ResponseType
from ..common.types import ReduceOp


class OpEntry:
    """One registered implementation (ref: HorovodOp subclasses +
    Enabled(), collective_operations.h:38-257)."""

    def __init__(self, name: str,
                 enabled: Callable[..., bool],
                 execute: Callable[..., np.ndarray]):
        self.name = name
        self.enabled = enabled
        self.execute = execute


class OperationManager:
    def __init__(self):
        self._ops: Dict[ResponseType, List[OpEntry]] = {}

    def register(self, response_type: ResponseType, entry: OpEntry):
        self._ops.setdefault(response_type, []).append(entry)

    def entries(self, response_type: ResponseType) -> List[OpEntry]:
        return list(self._ops.get(response_type, []))

    def select(self, response_type: ResponseType, **ctx) -> OpEntry:
        """First enabled op wins (ref: operation_manager.cc:99-116)."""
        for entry in self._ops.get(response_type, []):
            if entry.enabled(**ctx):
                return entry
        raise RuntimeError(
            f"no enabled op for {response_type!r} (ctx={ctx})"
        )


def build_default(backend) -> OperationManager:
    """Priority order mirrors the reference's CreateOperationManager
    (most specialized first): hierarchical ring > flat ring > star for
    allreduce; star for the other data ops; Adasum native/NumPy VHDD."""
    from ..backend import ring as ring_mod
    from ..backend.star import StarCollectivesMixin

    mgr = OperationManager()

    def _local(nbytes=0, reduce_op=None):
        return backend.size == 1

    # Allreduce executors take `owned=` (engine-set for fresh fusion/
    # prescale temporaries): the ring planes reduce owned buffers in
    # place; algorithms without an in-place path just ignore it.
    if backend.size == 1:
        mgr.register(ResponseType.ALLREDUCE, OpEntry(
            "LOCAL_ALLREDUCE", _local,
            lambda buf, rop, owned=False: backend.allreduce(buf, rop),
        ))
    else:
        mgr.register(ResponseType.ALLREDUCE, OpEntry(
            "SHM_ARENA_ALLREDUCE",
            lambda nbytes, reduce_op: ring_mod.arena_eligible(
                backend, nbytes, reduce_op),
            lambda buf, rop, owned=False: backend._arena_allreduce(
                buf, rop, owned=owned),
        ))
        mgr.register(ResponseType.ALLREDUCE, OpEntry(
            "HIERARCHICAL_RING_ALLREDUCE",
            lambda nbytes, reduce_op: ring_mod.hierarchical_eligible(
                backend, nbytes, reduce_op),
            lambda buf, rop, owned=False: backend._hierarchical_allreduce(
                buf, rop, owned=owned),
        ))
        mgr.register(ResponseType.ALLREDUCE, OpEntry(
            "RING_ALLREDUCE",
            lambda nbytes, reduce_op: ring_mod.ring_eligible(
                backend, nbytes, reduce_op),
            lambda buf, rop, owned=False: backend._ring_allreduce(
                buf, rop, owned=owned),
        ))
        mgr.register(ResponseType.ALLREDUCE, OpEntry(
            "STAR_ALLREDUCE",
            lambda nbytes, reduce_op: True,
            lambda buf, rop, owned=False: StarCollectivesMixin.allreduce(
                backend, buf, rop),
        ))

    mgr.register(ResponseType.ADASUM, OpEntry(
        "ADASUM_VHDD",
        lambda nbytes=0, reduce_op=None: True,
        lambda buf, rop=None, owned=False: backend.adasum_allreduce_all(buf),
    ))
    if backend.size > 1 and hasattr(backend, "_ring_allgatherv"):
        mgr.register(ResponseType.ALLGATHER, OpEntry(
            "HIERARCHICAL_ALLGATHER",
            lambda nbytes=0, ndim=1: ring_mod.hierarchical_allgather_eligible(
                backend, nbytes, ndim),
            backend._hierarchical_allgatherv,
        ))
        mgr.register(ResponseType.ALLGATHER, OpEntry(
            "RING_ALLGATHER",
            lambda nbytes=0, ndim=1: ring_mod.ring_allgather_eligible(
                backend, nbytes),
            backend._ring_allgatherv,
        ))
    mgr.register(ResponseType.ALLGATHER, OpEntry(
        "STAR_ALLGATHER",
        lambda **_: True,
        (lambda arr, dims: StarCollectivesMixin.allgatherv(
            backend, arr, dims))
        if backend.size > 1 else backend.allgatherv,
    ))
    mgr.register(ResponseType.BROADCAST, OpEntry(
        "STAR_BROADCAST",
        lambda **_: True,
        backend.broadcast,
    ))
    mgr.register(ResponseType.ALLTOALL, OpEntry(
        "STAR_ALLTOALL",
        lambda **_: True,
        backend.alltoallv,
    ))
    return mgr
