"""Response cache: skip re-negotiation for steady-state tensors.

Re-implementation of the reference's bit-vector response cache
(ref: horovod/common/response_cache.{h,cc}:44-167). Each cached Response
gets a stable cache bit; each cycle, ranks AND their hit bit-vectors
(so a tensor short-circuits negotiation only when *every* rank has it
queued and cached) and OR their invalid bits. Capacity default 1024
(ref: global_state.h:88), LRU eviction.

Under jit this machinery is unnecessary (the op set is static — the
cache's fast path is the compiled program itself); it serves the eager
process-mode engine.

Wire-compression note (docs/running.md "Wire compression"): the cached
object is the full negotiated Response, so the coordinator-assigned
wire codec id replays with it — on every rank, joined ranks included —
exactly like the executor channel. That is what makes codec choice
cache-replay-stable: a steady-state tensor keeps the codec it was
negotiated with even if HOROVOD_WIRE_COMPRESSION changes on rank 0
mid-run (the new policy applies from the next renegotiation, e.g.
after a shape-change invalidation), and no rank can ever replay a
response at a different wire width than its peers.
"""
from __future__ import annotations

import collections
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from ..common.message import Request, RequestType, Response, ResponseType


def _request_key(req: Request) -> Tuple:
    return (
        req.tensor_name,
        int(req.request_type),
        int(req.tensor_type),
        tuple(req.tensor_shape),
        req.root_rank,
        req.prescale_factor,
        req.postscale_factor,
        req.reduce_op,
    )


class CacheState:
    MISS = 0
    HIT = 1
    INVALID = 2


class ResponseCache:
    def __init__(self, capacity: int = 1024, registry=None):
        from ..common import telemetry

        if registry is None:
            registry = telemetry.default_registry()
        self._m_hits = registry.counter(
            "horovod_response_cache_hits_total",
            "Negotiations short-circuited by the response cache")
        self._m_misses = registry.counter(
            "horovod_response_cache_misses_total",
            "Requests with no usable cache entry")
        self._m_invalid = registry.counter(
            "horovod_response_cache_invalidations_total",
            "Cache entries dropped because the request signature changed")
        self.capacity = capacity
        # name -> (bit, key, response)
        self._by_name: Dict[str, Tuple[int, Tuple, Response]] = {}
        self._by_bit: Dict[int, str] = {}
        self._lru = collections.OrderedDict()  # name -> None, most recent last
        self._next_bit = 0
        self._free_bits: List[int] = []

    def cached(self, req: Request) -> int:
        ent = self._by_name.get(req.tensor_name)
        if ent is None:
            self._m_misses.inc()
            return CacheState.MISS
        bit, key, _ = ent
        if key == _request_key(req):
            # NOT counted as a hit yet: the cross-rank AND pass may still
            # requeue this request into full negotiation (peers not
            # ready). The controller calls count_hit() only when the
            # cached response is actually emitted, so the hit rate
            # measures fast-path responses served, not optimistic local
            # lookups.
            return CacheState.HIT
        self._m_invalid.inc()
        return CacheState.INVALID

    def count_hit(self):
        """One response actually served from the cache fast path."""
        self._m_hits.inc()

    def put(self, req: Request, resp: Response):
        if req.tensor_name in self._by_name:
            bit = self._by_name[req.tensor_name][0]
        elif self._free_bits:
            bit = self._free_bits.pop()
        elif len(self._by_name) < self.capacity:
            bit = self._next_bit
            self._next_bit += 1
        else:
            evict_name, _ = self._lru.popitem(last=False)
            bit = self._by_name.pop(evict_name)[0]
            self._by_bit.pop(bit, None)
        self._by_name[req.tensor_name] = (bit, _request_key(req), resp)
        self._by_bit[bit] = req.tensor_name
        self._lru.pop(req.tensor_name, None)
        self._lru[req.tensor_name] = None

    def has_bit(self, bit: int) -> bool:
        return bit in self._by_bit

    def peek_bit(self, name: str) -> Optional[int]:
        ent = self._by_name.get(name)
        return ent[0] if ent else None

    def get_response_by_bit(self, bit: int) -> Response:
        name = self._by_bit[bit]
        self._lru.pop(name, None)
        self._lru[name] = None
        return self._by_name[name][2]

    def erase(self, name: str):
        ent = self._by_name.pop(name, None)
        if ent:
            self._by_bit.pop(ent[0], None)
            self._free_bits.append(ent[0])
            self._lru.pop(name, None)

    def erase_bit(self, bit: int):
        name = self._by_bit.get(bit)
        if name is not None:
            self.erase(name)

    def bits_to_vector(self, bits: Set[int], nwords: int) -> List[int]:
        """Pack bit set into 64-bit words (ref: response_cache.h bitvector
        layout — 2 words per 64 entries)."""
        words = [0] * nwords
        for b in bits:
            words[b // 64] |= 1 << (b % 64)
        return words

    @staticmethod
    def vector_to_bits(words: List[int]) -> Set[int]:
        out = set()
        for wi, w in enumerate(words):
            while w:
                low = w & -w
                out.add(wi * 64 + low.bit_length() - 1)
                w ^= low
        return out

    def num_bits(self) -> int:
        return self._next_bit
