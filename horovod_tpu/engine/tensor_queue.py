"""Pending-tensor queue shared between framework threads and the engine's
background thread (ref: horovod/common/tensor_queue.{h,cc}:28-63).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from ..common.message import Request
from ..common.types import Status

DUPLICATE_NAME_ERROR = (
    "Requested to collective-op a tensor with the same name as another tensor "
    "that is currently being processed. "
    "(ref: horovod/common/common.h:163-166)"
)


@dataclass
class TensorTableEntry:
    """(ref: horovod/common/common.h TensorTableEntry)"""

    tensor_name: str
    tensor: Optional[np.ndarray]
    output: Optional[np.ndarray] = None
    root_rank: int = 0
    device: int = -1  # -1 = host
    callback: Optional[Callable[[Status, Optional[np.ndarray]], None]] = None
    # Alltoall splits (ref: operations.cc:979-1042)
    splits: Optional[List[int]] = None
    # Monotonic enqueue stamp (utils/clock): the tracing plane's
    # queue-dwell span runs from here to execution start.
    enqueued_ns: int = 0


class TensorQueue:
    def __init__(self, registry=None):
        from ..common import telemetry

        if registry is None:
            registry = telemetry.default_registry()
        self._m_latched = registry.counter(
            "horovod_tensor_queue_latched_errors_total",
            "Enqueues rejected because the engine already died "
            "(terminal status latched)")
        self._m_aborted = registry.counter(
            "horovod_tensor_queue_aborted_entries_total",
            "Pending entries failed by finalize() on engine death")
        self._lock = threading.Lock()
        self._tensor_table: Dict[str, TensorTableEntry] = {}
        self._message_queue: List[Request] = []
        # Event-driven cycles: the engine registers its wake event here
        # so an enqueue ends the background loop's coalescing wait
        # immediately instead of paying the full HOROVOD_CYCLE_TIME.
        self._wakeup: Optional[Callable[[], None]] = None
        # Set by finalize(): the engine died (transport failure, stall
        # abort, shutdown). Enqueues after that point fail IMMEDIATELY
        # with the terminal status instead of parking an entry no
        # background loop will ever pop — without this, the first
        # collective after a worker death hangs forever even though the
        # failure was already detected.
        self._final_status: Optional[Status] = None

    def set_wakeup(self, fn: Optional[Callable[[], None]]):
        self._wakeup = fn

    def add_to_tensor_queue(self, entry: TensorTableEntry, request: Request) -> Status:
        with self._lock:
            if self._final_status is not None:
                self._m_latched.inc()
                return self._final_status
            if entry.tensor_name in self._tensor_table:
                return Status.InvalidArgument(DUPLICATE_NAME_ERROR)
            self._tensor_table[entry.tensor_name] = entry
            self._message_queue.append(request)
        # Outside the lock: the wake target (an Event.set) never blocks,
        # but keeping callbacks out of the critical section is free.
        wake = self._wakeup
        if wake is not None:
            wake()
        return Status.OK()

    def pop_messages_from_queue(self) -> List[Request]:
        with self._lock:
            msgs, self._message_queue = self._message_queue, []
            return msgs

    def get_tensor_entries(self, names: List[str]) -> List[TensorTableEntry]:
        """Remove and return the entries for a response's tensors
        (ref: tensor_queue.cc GetTensorEntriesFromResponse)."""
        with self._lock:
            out = []
            for n in names:
                e = self._tensor_table.pop(n, None)
                if e is not None:
                    out.append(e)
            return out

    def get_tensor_entry(self, name: str) -> Optional[TensorTableEntry]:
        with self._lock:
            return self._tensor_table.get(name)

    def pop_entries_by_prefix(self, prefix: str) -> List[TensorTableEntry]:
        """Used to complete local JOIN entries when the all-joined response
        arrives (the JOIN Response carries no tensor names)."""
        with self._lock:
            names = [n for n in self._tensor_table if n.startswith(prefix)]
            return [self._tensor_table.pop(n) for n in names]

    def size(self) -> int:
        with self._lock:
            return len(self._tensor_table)

    def pending_names(self) -> List[str]:
        """Names of tensors still awaiting a response (for /status)."""
        with self._lock:
            return sorted(self._tensor_table)

    def finalize(self, status: Status):
        """Abort ALL pending entries with `status` and latch it as the
        terminal state (ref: tensor_queue.cc FinalizeTensorQueue). Every
        handle a framework thread is waiting on — not just the op that
        hit the failure — fails with the same reason, so N threads
        blocked on N tensors all unblock into the elastic recovery path
        at once."""
        with self._lock:
            self._final_status = status
            self._m_aborted.inc(len(self._tensor_table))
            for e in self._tensor_table.values():
                if e.callback:
                    e.callback(status, None)
            self._tensor_table.clear()
            self._message_queue.clear()
