"""Pending-tensor queue shared between framework threads and the engine's
background thread (ref: horovod/common/tensor_queue.{h,cc}:28-63).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from ..common.message import Request
from ..common.types import Status

DUPLICATE_NAME_ERROR = (
    "Requested to collective-op a tensor with the same name as another tensor "
    "that is currently being processed. "
    "(ref: horovod/common/common.h:163-166)"
)


@dataclass
class TensorTableEntry:
    """(ref: horovod/common/common.h TensorTableEntry)"""

    tensor_name: str
    tensor: Optional[np.ndarray]
    output: Optional[np.ndarray] = None
    root_rank: int = 0
    device: int = -1  # -1 = host
    callback: Optional[Callable[[Status, Optional[np.ndarray]], None]] = None
    # Alltoall splits (ref: operations.cc:979-1042)
    splits: Optional[List[int]] = None


class TensorQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self._tensor_table: Dict[str, TensorTableEntry] = {}
        self._message_queue: List[Request] = []

    def add_to_tensor_queue(self, entry: TensorTableEntry, request: Request) -> Status:
        with self._lock:
            if entry.tensor_name in self._tensor_table:
                return Status.InvalidArgument(DUPLICATE_NAME_ERROR)
            self._tensor_table[entry.tensor_name] = entry
            self._message_queue.append(request)
            return Status.OK()

    def pop_messages_from_queue(self) -> List[Request]:
        with self._lock:
            msgs, self._message_queue = self._message_queue, []
            return msgs

    def get_tensor_entries(self, names: List[str]) -> List[TensorTableEntry]:
        """Remove and return the entries for a response's tensors
        (ref: tensor_queue.cc GetTensorEntriesFromResponse)."""
        with self._lock:
            out = []
            for n in names:
                e = self._tensor_table.pop(n, None)
                if e is not None:
                    out.append(e)
            return out

    def get_tensor_entry(self, name: str) -> Optional[TensorTableEntry]:
        with self._lock:
            return self._tensor_table.get(name)

    def pop_entries_by_prefix(self, prefix: str) -> List[TensorTableEntry]:
        """Used to complete local JOIN entries when the all-joined response
        arrives (the JOIN Response carries no tensor names)."""
        with self._lock:
            names = [n for n in self._tensor_table if n.startswith(prefix)]
            return [self._tensor_table.pop(n) for n in names]

    def size(self) -> int:
        with self._lock:
            return len(self._tensor_table)

    def finalize(self, status: Status):
        """Abort all pending entries (ref: tensor_queue.cc FinalizeTensorQueue)."""
        with self._lock:
            for e in self._tensor_table.values():
                if e.callback:
                    e.callback(status, None)
            self._tensor_table.clear()
            self._message_queue.clear()
