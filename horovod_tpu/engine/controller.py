"""Coordinator protocol: rank-0 master/worker negotiation of ready tensors.

Re-implementation of the reference controller (ref: horovod/common/
controller.{h,cc}; protocol documented at controller.h:66-100):

  * every cycle, workers send a RequestList of newly-ready tensors to the
    coordinator (rank 0); the coordinator counts requests per tensor name
    (``IncrementTensorCount``, ref: controller.cc:837-860) — a tensor is
    ready when all ``size - joined_size`` ranks have requested it;
  * the coordinator validates cross-rank consistency (dtype/shape/op/root,
    ref: ConstructResponse, controller.cc:380-657) and answers with a
    (fused) ResponseList, or an ERROR response carrying the mismatch text;
  * responses are fused up to the fusion threshold
    (ref: FuseResponses, controller.cc:686-809);
  * a bit-vector response cache short-circuits negotiation for
    steady-state tensors (ref: ComputeResponseList fast path,
    controller.cc:63-358).

The transport is abstract (ref: controller.h:45-59 virtuals); the TCP
full-mesh backend provides gather/bcast/bitwise ops the way
MPIController does with MPI_Gather/Bcast (ref: mpi_controller.cc:88-199).
"""
from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..common import tracing
from ..common.exceptions import TransportError
from ..common.message import (
    Request,
    RequestList,
    RequestType,
    Response,
    ResponseList,
    ResponseType,
)
from ..common.types import DataType, ReduceOp, dtype_size
from ..utils import clock
from ..utils import env as env_cfg
from ..utils.logging import get_logger
from .response_cache import CacheState, ResponseCache
from .stall import StallInspector

logger = get_logger()

# Flag bits carried in the cache-coordination exchange
# (ref: response_cache.h CacheCoordinator flags).
_FLAG_HAS_UNCACHED = 1 << 0
_FLAG_SHUTDOWN = 1 << 1
# This rank has joined: the coordinator substitutes an all-ones hit
# vector for it in the AND pass (a joined rank participates in every
# cached collective with zeros, so it must not veto the intersection).
_FLAG_JOINED = 1 << 2
# Terminal abort verdict: the coordinator lost a rank mid-round (liveness
# declaration or socket death observed during its gather) and is
# delivering the attributed reason in place of the normal cache verdict
# — the payload carries a trailing reason string, and every rank turns
# it into the same tensor-less ERROR + shutdown a stall abort produces.
_FLAG_ABORT = 1 << 3

_ALL_ONES = 0xFFFFFFFFFFFFFFFF


class _NegotiationAborted(Exception):
    """Internal: negotiation ended in a terminal abort verdict; carries
    the attributed reason every rank's pending handles will fail with."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason

# Response types eligible for a pipelined executor channel. Everything
# else (JOIN / BARRIER / ERROR) is a fence: the engine drains all
# channels before running it, so it keeps channel 0.
_CHANNELED_TYPES = frozenset((
    ResponseType.ALLREDUCE,
    ResponseType.ADASUM,
    ResponseType.ALLGATHER,
    ResponseType.BROADCAST,
    ResponseType.ALLTOALL,
))


class ControllerTransport:
    """Abstract control-plane transport (ref: controller.h:45-59,133-146)."""

    rank: int
    size: int

    def gather_bytes(self, payload: bytes) -> Optional[List[bytes]]:
        """Workers → coordinator. Returns all payloads on rank 0, None elsewhere."""
        raise NotImplementedError

    def bcast_bytes(self, payload: Optional[bytes]) -> bytes:
        """Coordinator → workers."""
        raise NotImplementedError

    def allreduce_words(self, words: List[int], op: str) -> List[int]:
        """Element-wise bitwise 'and'/'or' across ranks
        (ref: CrossRankBitwiseAnd/Or, controller.h:141-143)."""
        raise NotImplementedError

    def barrier(self):
        raise NotImplementedError


@dataclass
class _TensorRecord:
    requests: List[Request] = field(default_factory=list)
    ranks: Set[int] = field(default_factory=set)


class Controller:
    def __init__(self, transport: ControllerTransport, size: int, rank: int,
                 timeline=None, registry=None, tracer=None):
        from ..common import telemetry

        # Coordinator-side timeline hook: negotiation phases are only
        # observable here (ref: timeline written on coordinator only,
        # operations.cc:416-429).
        self.timeline = timeline
        self.transport = transport
        self.size = size
        self.rank = rank
        self.is_coordinator = rank == 0
        self.registry = registry if registry is not None else telemetry.default_registry()
        self.response_cache = ResponseCache(env_cfg.cache_capacity(),
                                            registry=self.registry)
        self.cache_enabled = env_cfg.cache_enabled()
        self.fusion_threshold = env_cfg.fusion_threshold_bytes()
        self.stall_inspector = StallInspector(size, registry=self.registry)
        # Cross-rank telemetry: every HOROVOD_METRICS_SYNC_SECONDS each
        # rank piggybacks a scalar snapshot on the RequestList it already
        # gathers to rank 0; the coordinator folds them into the fleet
        # view (per-rank min/max/sum — a straggler is a rank-tagged
        # outlier). 0 disables. _last_metrics_push = 0 makes the very
        # first gather carry a snapshot, so the fleet view exists as
        # soon as the first negotiation completes.
        self.fleet = telemetry.FleetView(size) if self.is_coordinator else None
        self._metrics_sync_s = env_cfg.metrics_sync_seconds()
        self._last_metrics_push = 0.0
        # Coordinator state
        self.message_table: Dict[str, _TensorRecord] = {}
        # Join state (ref: global_state.h:103-107, controller.cc:220-308)
        self.joined_ranks: Set[int] = set()
        self.joined = False  # this rank called join
        # This cycle's cache hits, parked by cache bit so non-intersecting
        # hits can be re-queued into full negotiation.
        self._pending_cached: Dict[int, Request] = {}
        # Tensor metadata cache for fusion byte accounting
        self._sizes_by_name: Dict[str, int] = {}
        # Round-robin executor-channel cursor (coordinator only). The
        # assigned id rides the Response wire message, so workers follow
        # rank 0's HOROVOD_NUM_CHANNELS — read per cycle, so flipping it
        # between benchmark loops takes effect without a re-init. Cached
        # responses replay the channel they were negotiated with (it is
        # part of the cached Response on every rank), which keeps the
        # per-channel FIFO identical everywhere.
        self._next_channel = 0
        # -- tracing plane (common/tracing.py, docs/tracing.md) --------
        # Negotiated responses get a coordinator-assigned trace id
        # carried on the Response wire message (even id space);
        # cache-replayed responses use a deterministic per-rank replay
        # sequence (odd space — every rank emits the same cached set in
        # the same order, so local counters agree without wire bytes).
        self.tracer: Optional[tracing.Tracer] = tracer
        self._trace_seq = 0
        self._replay_seq = 0
        # Rank 0 accumulates every rank's span batches (piggybacked on
        # the telemetry push) for the merged /trace view.
        self.trace_collector = (
            tracing.TraceCollector(size) if self.is_coordinator else None)
        self._trace_cursor = 0
        # -- health plane (common/alerts.py, docs/health.md) -----------
        # Per-rank alert state rides the same telemetry piggyback:
        # `alert_push` (a callable returning the rank's firing set) is
        # merged into the push blob; `alert_sink` (rank 0's FleetAlerts)
        # ingests every gathered blob. Both wired by Engine.start() —
        # None until then, and None forever when the health plane is
        # off.
        self.alert_push = None
        self.alert_sink = None
        # -- events plane (common/events.py, docs/events.md) -----------
        # Lifecycle-event batches ride the same piggyback: `events_push`
        # (a callable returning {"batch", "anchor"} of new events) is
        # merged into the push blob; `events_sink` (rank 0's
        # FleetEvents) ingests every gathered blob. Wired by
        # Engine.start(); None when the events plane is off.
        self.events_push = None
        self.events_sink = None
        # Per-tensor request-arrival stamps (coordinator): feed the
        # NEGOTIATE span and the straggler attribution gauges — the
        # rank whose request lands last is the one everyone waited for.
        self._arrivals: Dict[str, Dict[int, int]] = {}
        self._neg_spans: Dict[str, Tuple[int, int, int]] = {}
        if self.is_coordinator:
            self._m_straggler = self.registry.gauge(
                "horovod_straggler_rank",
                "Rank whose request arrived last for the most recently "
                "negotiated collective (-1 before the first)")
            self._m_straggler.set(-1)
            self._m_neg_wait: Dict[int, object] = {}

    # ------------------------------------------------------------------
    def compute_response_list(
        self, messages: List[Request], shutdown: bool = False
    ) -> Tuple[ResponseList, bool]:
        """One negotiation cycle. Returns (responses, should_shutdown).

        A terminal abort verdict — the coordinator observed a rank die
        mid-round (liveness declaration severing its socket, or a
        socket-level death), broadcast the attributed reason, and every
        rank converged on it — surfaces as the same tensor-less ERROR +
        shutdown a stall abort produces, so the engine fails every
        pending handle with "rank 2 (host X) declared dead...", not a
        bare transport error.
        """
        try:
            return self._compute_response_list(messages, shutdown)
        except _NegotiationAborted as exc:
            err = Response(ResponseType.ERROR, [], error_message=exc.reason)
            return ResponseList([err], shutdown=True), True

    def _compute_response_list(
        self, messages: List[Request], shutdown: bool = False
    ) -> Tuple[ResponseList, bool]:
        """One negotiation cycle. Returns (responses, should_shutdown).

        Mirrors Controller::ComputeResponseList (controller.cc:63-358):
        cache fast path first, then full negotiation for uncached tensors.
        """
        # --- split messages into cache hits and misses -----------------
        uncached: List[Request] = []
        local_invalid_bits: Set[int] = set()
        for req in messages:
            if req.request_type == RequestType.JOIN:
                self.joined = True
                uncached.append(req)
                continue
            state = (
                self.response_cache.cached(req) if self.cache_enabled else CacheState.MISS
            )
            if state == CacheState.HIT:
                self._pending_cached[
                    self.response_cache.peek_bit(req.tensor_name)
                ] = req
            else:
                if state == CacheState.INVALID:
                    # Signature changed (e.g. new shape). Announce the old
                    # bit in the OR pass so every rank drops its entry in
                    # the same cycle (ref: CacheCoordinator invalid-bit
                    # second pass, response_cache.cc) — otherwise peers
                    # that HIT on the stale entry would park the request
                    # forever while this rank re-negotiates it.
                    local_invalid_bits.add(
                        self.response_cache.peek_bit(req.tensor_name)
                    )
                    self.response_cache.erase(req.tensor_name)
                uncached.append(req)

        responses: List[Response] = []

        # --- cache coordination: ONE fused control round ---------------
        # Each rank gathers [flags, pending-hit bits, invalid bits] to
        # rank 0, which computes the AND-intersection, the OR of flags
        # and invalid bits, AND the requeue-induced HAS_UNCACHED (a
        # pending bit outside the final intersection means its owner
        # re-negotiates) in one shot, then broadcasts the verdict. The
        # reference — and this engine until the pipelined-execution PR —
        # spends two sequential word-allreduce rounds on this (AND pass,
        # then OR pass); since a fully cached steady-state cycle is
        # nothing BUT cache coordination, that second round was most of
        # a small op's enqueue-to-complete latency.
        if self.cache_enabled:
            nwords = (max(self.response_cache.num_bits(), 1) + 63) // 64
            flags = 0
            # HAS_UNCACHED: a rank overdue for a telemetry push raises
            # the flag too — in a fully-cached steady state no gather
            # would otherwise run, and the fleet view would go stale
            # exactly when the job is busiest. The cost is one ordinary
            # (empty) negotiation round per sync interval.
            if uncached or self._telemetry_due():
                flags |= _FLAG_HAS_UNCACHED
            if shutdown:
                flags |= _FLAG_SHUTDOWN
            if self.joined:
                flags |= _FLAG_JOINED
            pending_words = self.response_cache.bits_to_vector(
                set(self._pending_cached), nwords)
            invalid_words = self.response_cache.bits_to_vector(
                local_invalid_bits, nwords)
            flags, common_bits, global_invalid = self._coordinate_cache(
                flags, pending_words, invalid_words)
            shutdown = bool(flags & _FLAG_SHUTDOWN)
            any_uncached = bool(flags & _FLAG_HAS_UNCACHED)

            # Hits outside the (invalid-pruned) intersection go back to
            # full negotiation — peers weren't ready, or the entry was
            # invalidated somewhere. The cache entry itself stays unless
            # globally invalidated below.
            for bit in sorted(set(self._pending_cached) - common_bits):
                uncached.append(self._pending_cached.pop(bit))

            # Drop globally-invalidated entries everywhere.
            for bit in global_invalid:
                if self.response_cache.has_bit(bit):
                    self.response_cache.erase_bit(bit)

            # Emit cached responses common to all ranks, in stable bit
            # order. A joined rank emits them too — it must take part in
            # the data plane (with zero contributions) or peers block.
            # Each replay gets a fresh trace id from the deterministic
            # replay sequence (identical on every rank: same bits, same
            # order, same counter) — a shallow copy, so the cached
            # entry itself stays untouched.
            for bit in sorted(common_bits):
                if bit in self._pending_cached or (
                    self.joined and self.response_cache.has_bit(bit)
                ):
                    resp = self.response_cache.get_response_by_bit(bit)
                    self._replay_seq += 1
                    responses.append(replace(
                        resp, trace_id=(self._replay_seq << 1) | 1))
                    self._pending_cached.pop(bit, None)
                    self.response_cache.count_hit()
        else:
            any_uncached = True

        # --- full negotiation for uncached tensors ---------------------
        if any_uncached or not self.cache_enabled:
            req_list = RequestList(uncached, shutdown=shutdown)
            # Attach at HALF the interval once a gather is happening
            # anyway: a rank dragged into another rank's telemetry-forced
            # round publishes too and resets its timer, so per-rank
            # deadlines coalesce into ~one forced round per interval
            # instead of random-walking apart into world-size rounds.
            if self._telemetry_elapsed() >= self._metrics_sync_s / 2 > 0:
                from ..common import telemetry as _telemetry

                self._last_metrics_push = time.monotonic()
                # Tracing piggyback: new flight-recorder events since
                # the last push ride the same blob, so trace collection
                # costs no extra control round (docs/tracing.md).
                extra = {}
                if self.tracer is not None and self.tracer.enabled:
                    evs, self._trace_cursor = \
                        self.tracer.recorder.batch_since(self._trace_cursor)
                    extra = {"spans": evs, "anchor": clock.anchor_meta()}
                if self.alert_push is not None:
                    try:
                        extra["alerts"] = self.alert_push()
                    except Exception:  # alerts must never stall a cycle
                        pass
                if self.events_push is not None:
                    try:
                        ev_sec = self.events_push()
                        if ev_sec:
                            extra["events"] = ev_sec
                    except Exception:  # events must never stall a cycle
                        pass
                req_list.telemetry = _telemetry.encode_push(
                    self.registry, self.rank, extra=extra or None)
            try:
                with self._span("ctrl.gather"):
                    gathered = self.transport.gather_bytes(
                        req_list.serialize())
            except TransportError as exc:
                if not self.is_coordinator:
                    raise
                # A rank died while the coordinator gathered request
                # lists. Workers are (or will be) parked on THIS
                # round's response broadcast — deliver the attributed
                # verdict there, best-effort, then converge locally.
                reason = self._abort_reason(exc)
                err = Response(ResponseType.ERROR, [],
                               error_message=reason)
                self._bcast_lossy(
                    ResponseList([err], shutdown=True).serialize())
                raise _NegotiationAborted(reason) from exc
            if self.is_coordinator:
                negotiated: List[Response] = []
                ready_names: List[str] = []
                joined_before = len(self.joined_ranks)
                for peer_rank, payload in enumerate(gathered):
                    rl = RequestList.deserialize(payload)
                    if rl.telemetry is not None:
                        if self.fleet is not None:
                            self.fleet.ingest(rl.telemetry,
                                              rank_hint=peer_rank)
                        if self.trace_collector is not None:
                            self.trace_collector.ingest_blob(
                                peer_rank, rl.telemetry)
                        if self.alert_sink is not None:
                            self.alert_sink.ingest_blob(
                                peer_rank, rl.telemetry)
                        if self.events_sink is not None:
                            self.events_sink.ingest_blob(
                                peer_rank, rl.telemetry)
                    shutdown = shutdown or rl.shutdown
                    for req in rl.requests:
                        if req.request_type == RequestType.JOIN:
                            self.joined_ranks.add(req.request_rank)
                            continue
                        if self._increment_tensor_count(req):
                            ready_names.append(req.tensor_name)
                if len(self.joined_ranks) != joined_before:
                    # A new join lowers the readiness bar; re-check pending
                    # tensors (ref: controller.cc:220-231).
                    need = self.size - len(self.joined_ranks)
                    for n, rec in self.message_table.items():
                        if n not in ready_names and len(rec.ranks) >= need:
                            ready_names.append(n)
                # All ranks joined → emit JOIN response resetting state
                # (ref: controller.cc:263-308). Appended AFTER this
                # cycle's data responses: JOIN is an engine fence, and
                # placing it last means the drain it triggers covers the
                # final collectives negotiated in the same cycle — a
                # completed join handle guarantees every earlier op of
                # that rank has finished.
                join_resp = None
                if self.joined_ranks and len(self.joined_ranks) == self.size:
                    join_resp = Response(
                        ResponseType.JOIN,
                        last_joined_rank=max(self.joined_ranks))
                    self.joined_ranks.clear()
                new_responses = [self._construct_response(n) for n in ready_names]
                fused = self._fuse_responses(new_responses)
                self._assign_channels(fused)
                self._assign_codecs(fused)
                negotiated.extend(fused)
                if join_resp is not None:
                    negotiated.append(join_resp)
                stall_reason = self.stall_inspector.check()
                if stall_reason:
                    shutdown = True
                    # Tensor-less ERROR response: carries the stall
                    # diagnosis to every rank inside the existing wire
                    # format; the engine finalizes ALL pending handles
                    # with it (engine.py _run_loop_once).
                    negotiated.append(Response(
                        ResponseType.ERROR, [], error_message=stall_reason
                    ))
                self._assign_trace_ids(negotiated)
                # Broadcast only the negotiated responses; every rank
                # prepends its (identical) cached fast-path list locally.
                try:
                    with self._span("ctrl.bcast"):
                        self.transport.bcast_bytes(
                            ResponseList(negotiated,
                                         shutdown=shutdown).serialize()
                        )
                except TransportError:
                    # Same contract as the cache-verdict broadcast: the
                    # dead peer is severed, survivors received the
                    # round, the next gather aborts with attribution.
                    pass
                resp_list = ResponseList(responses + negotiated, shutdown)
            else:
                with self._span("ctrl.bcast"):
                    recv = ResponseList.deserialize(
                        self.transport.bcast_bytes(None))
                resp_list = ResponseList(responses + recv.responses, recv.shutdown)
            # Populate cache from negotiated responses on every rank so
            # cache bit assignment stays rank-consistent.
            if self.cache_enabled:
                for resp in resp_list.responses:
                    self._maybe_cache(resp)
            if any(
                r.response_type == ResponseType.JOIN for r in resp_list.responses
            ):
                self.joined = False
            return resp_list, resp_list.shutdown

        return ResponseList(responses, shutdown=shutdown), shutdown

    # ------------------------------------------------------------------
    @staticmethod
    def _pack_coord(flags: int, a: Sequence[int], b: Sequence[int],
                    reason: str = "") -> bytes:
        # Trailing reason bytes (present iff _FLAG_ABORT): decoders that
        # stop after the word vectors stay compatible.
        return struct.pack(f"<QII{len(a)}Q{len(b)}Q",
                           flags, len(a), len(b), *a, *b) \
            + reason.encode("utf-8", "replace")

    @staticmethod
    def _unpack_coord(buf) -> Tuple[int, List[int], List[int], str]:
        flags, na, nb = struct.unpack_from("<QII", buf, 0)
        off = struct.calcsize("<QII")
        words = struct.unpack_from(f"<{na + nb}Q", buf, off)
        reason = ""
        if flags & _FLAG_ABORT:
            reason = bytes(buf[off + 8 * (na + nb):]).decode(
                "utf-8", "replace")
        return flags, list(words[:na]), list(words[na:]), reason

    # ------------------------------------------------------------------
    @staticmethod
    def _abort_reason(exc: TransportError) -> str:
        """Attributed abort reason for a transport failure the
        coordinator observed mid-round. A liveness verdict (root_cause)
        is already the full story; a socket-level death gets the peer
        rank stamped on so survivors hear 'rank 2 died', never just
        'connection reset'."""
        if getattr(exc, "root_cause", None):
            return str(exc)
        peer = getattr(exc, "peer", None)
        if peer is not None:
            return (f"rank {peer} lost during negotiation "
                    f"(observed by the coordinator): {exc}")
        return f"coordinator negotiation transport failure: {exc}"

    def _bcast_lossy(self, payload: bytes):
        """Best-effort terminal-verdict broadcast: a second dead peer
        must not stop the verdict reaching the remaining survivors."""
        lossy = getattr(self.transport, "bcast_bytes_lossy", None)
        try:
            if lossy is not None:
                lossy(payload)
            else:
                self.transport.bcast_bytes(payload)
        except TransportError:  # pragma: no cover - mesh collapsing
            pass

    def _coordinate_cache(
        self, flags: int, pending_words: List[int],
        invalid_words: List[int],
    ) -> Tuple[int, Set[int], Set[int]]:
        """Fused cache-coordination round: one gather + one broadcast.
        Returns (global flags, common bit set, globally-invalid bit
        set). Vector lengths may differ across ranks while cache sizes
        converge — rank 0 zero-extends (and extends a joined rank's
        implicit all-ones hit vector to the full width, so a joined
        rank can never veto bits its own cache hasn't grown to)."""
        payload = self._pack_coord(flags, pending_words, invalid_words)
        try:
            gathered = self.transport.gather_bytes(payload)
        except TransportError as exc:
            if not self.is_coordinator:
                raise
            # A rank died (or was declared dead by the liveness plane)
            # while the coordinator gathered this round. The workers'
            # next recv is THIS round's verdict broadcast, so the abort
            # must ride the coord-verdict payload — then every rank
            # raises the same attributed shutdown.
            reason = self._abort_reason(exc)
            self._bcast_lossy(self._pack_coord(
                _FLAG_ABORT | _FLAG_SHUTDOWN, [], [], reason))
            raise _NegotiationAborted(reason) from exc
        if self.is_coordinator:
            decoded = [self._unpack_coord(b)[:3] for b in gathered]
            nw = max(1, max(len(p) for _, p, _ in decoded),
                     max(len(i) for _, _, i in decoded))
            out_flags = 0
            common = [_ALL_ONES] * nw
            or_pending = [0] * nw
            or_invalid = [0] * nw
            for fl, pend, inv in decoded:
                out_flags |= fl & (_FLAG_HAS_UNCACHED | _FLAG_SHUTDOWN)
                joined = bool(fl & _FLAG_JOINED)
                for w in range(nw):
                    p = pend[w] if w < len(pend) else 0
                    hit = _ALL_ONES if joined else p
                    common[w] &= hit
                    or_pending[w] |= p
                    if w < len(inv):
                        or_invalid[w] |= inv[w]
            # Invalidated bits leave the intersection; any pending bit
            # outside the final intersection means its rank requeues it
            # into full negotiation, so the negotiation gather must run.
            requeue = 0
            for w in range(nw):
                common[w] &= ~or_invalid[w] & _ALL_ONES
                requeue |= or_pending[w] & ~common[w]
            if requeue:
                out_flags |= _FLAG_HAS_UNCACHED
            verdict = self._pack_coord(out_flags, common, or_invalid)
            try:
                self.transport.bcast_bytes(verdict)
            except TransportError:
                # A peer died between this round's gather and its
                # broadcast. The dead peer is severed; the SURVIVORS all
                # received the verdict (bcast attempts every peer), so
                # the round is consistent — finish it locally and let
                # the next round's gather hit the severed peer and
                # broadcast the attributed abort in lockstep.
                pass
        else:
            verdict = self.transport.bcast_bytes(None)
        out_flags, common, or_invalid, reason = self._unpack_coord(verdict)
        if out_flags & _FLAG_ABORT:
            raise _NegotiationAborted(
                reason or "negotiation aborted by the coordinator")
        return (out_flags, ResponseCache.vector_to_bits(common),
                ResponseCache.vector_to_bits(or_invalid))

    # ------------------------------------------------------------------
    def _assign_channels(self, responses: List[Response]):
        """Executor-channel assignment (coordinator side; the id rides
        the Response wire message so every rank follows it). Under the
        default "size" policy the highest channel is a latency lane:
        small responses (<= HOROVOD_LATENCY_CHANNEL_BYTES) go there and
        bulk responses round-robin over the remaining channels — a
        blind round-robin would park every other small op behind a
        streaming multi-MB collective and re-create the head-of-line
        blocking the channels exist to remove. "rr" round-robins
        everything (all inputs are negotiated, so either policy is
        identical on every rank)."""
        nchan = env_cfg.num_channels()
        if nchan <= 1:
            return
        size_policy = env_cfg.channel_policy() == "size"
        small = env_cfg.latency_channel_bytes()
        bulk = nchan - 1 if size_policy else nchan
        for resp in responses:
            if resp.response_type not in _CHANNELED_TYPES:
                continue
            if size_policy and sum(
                self._byte_size(resp, n) for n in resp.tensor_names
            ) <= small:
                resp.channel = nchan - 1
                continue
            if self._next_channel >= bulk:
                self._next_channel = 0
            resp.channel = self._next_channel
            self._next_channel = (self._next_channel + 1) % bulk

    # ------------------------------------------------------------------
    def _assign_codecs(self, responses: List[Response]):
        """Wire-codec assignment (coordinator side; the codec id rides
        the Response wire message next to the channel id, so every
        rank — workers and joined ranks replaying cached responses
        alike — applies the same codec to the same response's frames;
        a per-rank env read here would half-compress a collective and
        desync the stream). Policy (docs/running.md "Wire
        compression"): fp32 SUM allreduces at or above
        HOROVOD_WIRE_COMPRESSION_MIN_BYTES get the configured codec
        (auto = bf16, the TPU-native pick); with the int8 opt-in,
        responses on the size policy's latency lane quantize to
        int8-with-scale instead. MIN/MAX/PRODUCT reduces and non-fp32
        payloads always ship full-width — quantizing a comparison
        reduce changes its semantics, not just its precision. Every
        input is negotiated, so the decision is deterministic from the
        wire message alone."""
        mode = env_cfg.wire_compression_mode()
        if mode == "none":
            return
        from ..common import compression

        wide = (compression.CODEC_FP16 if mode == "fp16"
                else compression.CODEC_BF16)
        min_bytes = env_cfg.wire_compression_min_bytes()
        nchan = env_cfg.num_channels()
        latency_ch = (nchan - 1
                      if nchan > 1 and env_cfg.channel_policy() == "size"
                      else None)
        int8_on = env_cfg.wire_compression_int8()
        for resp in responses:
            if (resp.response_type != ResponseType.ALLREDUCE
                    or resp.error_message):
                continue
            if DataType(resp.tensor_type) != DataType.FLOAT32:
                continue
            if resp.reduce_op not in (0, int(ReduceOp.SUM)):
                continue
            nbytes = sum(self._byte_size(resp, n)
                         for n in resp.tensor_names)
            if (int8_on and latency_ch is not None
                    and resp.channel == latency_ch):
                # int8 is variable-width (scale header), so only the
                # star path ships it compressed — and only STAR-BOUND
                # sizes may carry the assignment: a ring/arena-eligible
                # payload would pay the engine's coarse int8 grid
                # projection (4x accuracy loss) while shipping
                # full-width anyway (zero savings). ring_threshold is
                # launcher-propagated like every data-plane knob, so
                # the gate is collectively consistent.
                from ..backend.ring import ring_threshold

                if nbytes < ring_threshold():
                    resp.codec = compression.CODEC_INT8
                    continue
            if nbytes >= min_bytes:
                resp.codec = wide

    # ------------------------------------------------------------------
    # tracing plane (docs/tracing.md)
    def _span(self, name: str):
        t = self.tracer
        if t is None:
            return tracing.NOOP_SPAN
        return t.span(name, cat=tracing.CAT_NEGOTIATE)

    def _assign_trace_ids(self, responses: List[Response]):
        """Coordinator: stamp every negotiated response (fences and
        errors included) with a fresh trace id — carried on the wire,
        so every rank's spans for this collective share it — and emit
        the NEGOTIATE span (first request arrival → ready) under that
        id, naming the straggler."""
        for resp in responses:
            self._trace_seq += 1
            resp.trace_id = self._trace_seq << 1
            if self.tracer is None or not self.tracer.enabled:
                continue
            info = None
            for n in resp.tensor_names:
                info = self._neg_spans.pop(n, None) or info
            if info is not None:
                first, last, straggler = info
                self.tracer.emit(
                    "negotiate", tracing.CAT_NEGOTIATE, first,
                    max(last - first, 0), trace_id=resp.trace_id,
                    args={"tensors": len(resp.tensor_names),
                          "straggler": straggler})

    def collect_local(self):
        """Fold this rank's newest flight-recorder events into the
        collector (rank 0 render-time freshness; the collector dedups
        by sequence number, so overlap with the push path is free)."""
        if self.trace_collector is None or self.tracer is None:
            return
        self.trace_collector.ingest(
            self.rank, self.tracer.recorder.snapshot(), clock.anchor_meta())

    def _note_negotiated(self, name: str):
        """Straggler attribution for one ready tensor: per-rank
        negotiation wait (how long the collective waited on each rank
        past the first arrival) and the straggler gauge (the last
        rank in). Gauges live on the coordinator's registry; the fleet
        view redistributes them."""
        arr = self._arrivals.pop(name, None)
        if not arr:
            return
        if len(arr) < 2:
            self._neg_spans[name] = (
                next(iter(arr.values())), next(iter(arr.values())), -1)
            return
        first = min(arr.values())
        last_rank = max(arr, key=arr.get)
        for r, t in arr.items():
            g = self._m_neg_wait.get(r)
            if g is None:
                g = self._m_neg_wait[r] = self.registry.gauge(
                    "horovod_negotiation_wait_seconds",
                    "How long the most recent collective's negotiation "
                    "waited on this rank past the first request arrival",
                    labels={"rank": str(r)})
            g.set((t - first) / 1e9)
        self._m_straggler.set(last_rank)
        self._neg_spans[name] = (first, arr[last_rank], last_rank)

    # ------------------------------------------------------------------
    def _telemetry_elapsed(self) -> float:
        return time.monotonic() - self._last_metrics_push

    def _telemetry_due(self) -> bool:
        return (self._metrics_sync_s > 0
                and self._telemetry_elapsed() >= self._metrics_sync_s)

    # ------------------------------------------------------------------
    def _increment_tensor_count(self, req: Request) -> bool:
        """(ref: IncrementTensorCount, controller.cc:837-860)"""
        if self.timeline is not None:
            if req.tensor_name not in self.message_table:
                # First rank's request opens the NEGOTIATE_<OP> phase
                # (ref: Timeline::NegotiateStart, timeline.h:87-95).
                self.timeline.negotiate_start(
                    req.tensor_name, req.request_type.name
                )
            self.timeline.negotiate_rank_ready(
                req.tensor_name, req.request_rank
            )
        rec = self.message_table.setdefault(req.tensor_name, _TensorRecord())
        if req.request_rank not in rec.ranks:
            rec.requests.append(req)
            rec.ranks.add(req.request_rank)
            self._arrivals.setdefault(
                req.tensor_name, {})[req.request_rank] = clock.mono_ns()
        self.stall_inspector.record(req.tensor_name, req.request_rank)
        return len(rec.ranks) == self.size - len(self.joined_ranks)

    # ------------------------------------------------------------------
    def _construct_response(self, name: str) -> Response:
        """Validate cross-rank consistency and build the Response
        (ref: ConstructResponse, controller.cc:380-657)."""
        rec = self.message_table.pop(name)
        if self.timeline is not None:
            # Negotiation closes the moment the response is formed
            # (ref: Timeline::NegotiateEnd, timeline.h:96-104).
            self.timeline.negotiate_end(
                name, rec.requests[0].request_type.name
            )
        self.stall_inspector.remove(name)
        self._note_negotiated(name)
        reqs = rec.requests
        first = reqs[0]

        def error(msg: str) -> Response:
            # Always name the failing op so a user with hundreds of
            # tensors in flight can find the culprit
            # (ref: controller.cc error strings are likewise prefixed).
            return Response(ResponseType.ERROR, [name],
                            error_message=f"[{name}] {msg}")

        for r in reqs[1:]:
            if r.request_type != first.request_type:
                return error(
                    f"Mismatched collective operations: One rank requested "
                    f"{first.request_type.name}, another {r.request_type.name}."
                )
            if r.tensor_type != first.tensor_type:
                return error(
                    f"Mismatched data types: One rank had type "
                    f"{DataType(first.tensor_type).name}, another "
                    f"{DataType(r.tensor_type).name}."
                )
            if (
                r.prescale_factor != first.prescale_factor
                or r.postscale_factor != first.postscale_factor
            ):
                return error("Mismatched prescale/postscale factors.")
            if r.reduce_op != first.reduce_op:
                return error(
                    f"Mismatched reduce ops: One rank requested op "
                    f"{first.reduce_op}, another {r.reduce_op}."
                )

        rt = first.request_type
        # Join compatibility gate FIRST: with joined ranks, not every rank
        # has a request, so per-rank validation below would miss entries
        # (ref: controller.cc:487-494,568-571 — only allreduce/barrier
        # support join; Adasum's power-of-2 requirement also breaks).
        if self.joined_ranks and rt not in (
            RequestType.ALLREDUCE,
            RequestType.BARRIER,
        ):
            return error(
                f"{rt.name} is not supported while some ranks have joined."
            )
        if self.joined_ranks and first.reduce_op not in (
            0, int(ReduceOp.SUM)
        ):
            # Joined ranks contribute zeros — the identity only for SUM
            # (ref: JoinOp zero-contribution semantics).
            return error(
                "MIN/MAX/PRODUCT allreduce is not supported while some "
                "ranks have joined."
            )

        tensor_sizes: List[int] = []
        if rt == RequestType.ALLREDUCE or rt == RequestType.ADASUM:
            for r in reqs[1:]:
                if tuple(r.tensor_shape) != tuple(first.tensor_shape):
                    return error(
                        f"Mismatched allreduce tensor shapes: One rank sent "
                        f"{list(first.tensor_shape)}, another {list(r.tensor_shape)}."
                    )
            resp_type = (
                ResponseType.ADASUM if rt == RequestType.ADASUM else ResponseType.ALLREDUCE
            )
        elif rt == RequestType.ALLGATHER:
            # First dim may differ; trailing dims must match
            # (ref: controller.cc allgather shape checks).
            by_rank = {r.request_rank: r for r in reqs}
            for r in reqs[1:]:
                if r.tensor_shape[1:] != first.tensor_shape[1:]:
                    return error(
                        "Mismatched allgather tensor shapes: all dimensions "
                        "except the first must match."
                    )
                if len(r.tensor_shape) != len(first.tensor_shape):
                    return error("Mismatched allgather tensor ranks.")
            tensor_sizes = [
                int(by_rank[i].tensor_shape[0]) if by_rank[i].tensor_shape else 0
                for i in range(self.size)
            ]
            resp_type = ResponseType.ALLGATHER
        elif rt == RequestType.BROADCAST:
            for r in reqs[1:]:
                if r.root_rank != first.root_rank:
                    return error(
                        f"Mismatched broadcast root ranks: One rank sent root "
                        f"{first.root_rank}, another {r.root_rank}."
                    )
                if r.request_rank != first.root_rank and tuple(r.tensor_shape) != tuple(
                    first.tensor_shape
                ):
                    # Non-root shapes must match root's.
                    pass  # output allocated from root shape; tolerate
            resp_type = ResponseType.BROADCAST
        elif rt == RequestType.ALLTOALL:
            resp_type = ResponseType.ALLTOALL
        elif rt == RequestType.BARRIER:
            resp_type = ResponseType.BARRIER
        else:
            return error(f"Unsupported request type {rt}")

        return Response(
            response_type=resp_type,
            tensor_names=[name],
            devices=[r.device for r in reqs],
            tensor_sizes=tensor_sizes,
            tensor_type=first.tensor_type,
            prescale_factor=first.prescale_factor,
            postscale_factor=first.postscale_factor,
            tensor_shapes=[tuple(first.tensor_shape)],
            reduce_op=first.reduce_op,
        )

    # ------------------------------------------------------------------
    def _response_bytes(self, resp: Response, req: Request) -> int:
        n = 1
        for d in req.tensor_shape:
            n *= d
        return n * dtype_size(DataType(resp.tensor_type))

    def _fuse_responses(self, responses: List[Response]) -> List[Response]:
        """Greedy fusion of same-type/dtype allreduce responses up to the
        fusion threshold (ref: FuseResponses, controller.cc:686-809, with
        the dtype look-ahead collapsed into a full scan)."""
        fused: List[Response] = []
        pending = [r for r in responses]
        while pending:
            base = pending.pop(0)
            if base.response_type not in (ResponseType.ALLREDUCE,):
                fused.append(base)
                continue
            base_bytes = sum(self._byte_size(base, n) for n in base.tensor_names)
            i = 0
            while i < len(pending):
                cand = pending[i]
                if (
                    cand.response_type == base.response_type
                    and cand.tensor_type == base.tensor_type
                    and cand.devices == base.devices
                    and cand.prescale_factor == base.prescale_factor
                    and cand.postscale_factor == base.postscale_factor
                    and cand.reduce_op == base.reduce_op
                    and not cand.error_message
                ):
                    cand_bytes = sum(self._byte_size(cand, n) for n in cand.tensor_names)
                    if base_bytes + cand_bytes <= self.fusion_threshold:
                        base.tensor_names.extend(cand.tensor_names)
                        base.tensor_sizes.extend(cand.tensor_sizes)
                        base.tensor_shapes.extend(cand.tensor_shapes)
                        base_bytes += cand_bytes
                        pending.pop(i)
                        continue
                i += 1
            fused.append(base)
        return fused

    def _byte_size(self, resp: Response, name: str) -> int:
        # Byte size recorded at request time. A coordinator that joined
        # never enqueued the tensor, so derive the size from the
        # response's own shape+dtype rather than defaulting to 0 (which
        # would let such responses fuse past the threshold unbounded).
        n = self._sizes_by_name.get(name)
        if n is not None:
            return n
        try:
            idx = resp.tensor_names.index(name)
            count = 1
            for d in resp.tensor_shapes[idx]:
                count *= d
            return count * dtype_size(DataType(resp.tensor_type))
        except (ValueError, IndexError):
            return 0

    def record_tensor_size(self, name: str, nbytes: int):
        self._sizes_by_name[name] = nbytes

    # ------------------------------------------------------------------
    def _maybe_cache(self, resp: Response):
        """Populate the cache from a freshly negotiated response. The key
        is built purely from Response fields so every rank — including
        joined ranks that never issued the request — assigns identical
        cache bits (ref: response_cache.cc put-from-response). Single-
        tensor responses only: the reference caches pre-fusion responses
        and re-fuses cached hits (ref: controller.cc:174-203); fused
        groups here re-negotiate."""
        if resp.response_type in (
            ResponseType.ALLREDUCE,
            ResponseType.ADASUM,
        ) and not resp.error_message and len(resp.tensor_names) == 1:
            key_req = Request(
                request_rank=0,
                request_type=RequestType.ADASUM
                if resp.response_type == ResponseType.ADASUM
                else RequestType.ALLREDUCE,
                tensor_type=DataType(resp.tensor_type),
                tensor_name=resp.tensor_names[0],
                root_rank=0,
                tensor_shape=tuple(resp.tensor_shapes[0])
                if resp.tensor_shapes
                else (),
                prescale_factor=resp.prescale_factor,
                postscale_factor=resp.postscale_factor,
                # Without echoing the negotiated reduce_op the key never
                # matches the live request (which carries SUM=1), so every
                # steady-state lookup came back INVALID and the cache fast
                # path never engaged — invisible until the hit/miss
                # counters existed.
                reduce_op=resp.reduce_op,
            )
            self.response_cache.put(key_req, resp)

    def synchronize_parameters(self, params: bytes) -> bytes:
        """Coordinator broadcasts autotuner parameters
        (ref: Controller::SynchronizeParameters, controller.cc:34-48)."""
        return self.transport.bcast_bytes(params if self.is_coordinator else None)
