"""Stall inspector: coordinator-side watchdog for stuck negotiations
(ref: horovod/common/stall_inspector.{h,cc}:30-96).

Warns when a tensor has been submitted by some ranks but is missing on
others for > HOROVOD_STALL_CHECK_TIME_SECONDS (default 60); optionally
aborts after HOROVOD_STALL_SHUTDOWN_TIME_SECONDS.
"""
from __future__ import annotations

import time
from typing import Dict, List, Set, Tuple

from ..utils import env as env_cfg
from ..utils.logging import get_logger

logger = get_logger()


class StallInspector:
    def __init__(self, size: int):
        self.size = size
        self.enabled = not env_cfg.get_bool(env_cfg.STALL_CHECK_DISABLE, False)
        self.warning_time = env_cfg.get_float(
            env_cfg.STALL_CHECK_TIME, env_cfg.DEFAULT_STALL_WARNING_SECONDS
        )
        self.shutdown_time = env_cfg.get_float(env_cfg.STALL_SHUTDOWN_TIME, 0.0)
        self.last_check = time.monotonic()
        # tensor name -> (first-seen time, set of ready ranks)
        self.pending: Dict[str, Tuple[float, Set[int]]] = {}
        self.warned: Set[str] = set()

    def record(self, name: str, rank: int):
        now = time.monotonic()
        if name not in self.pending:
            self.pending[name] = (now, set())
        self.pending[name][1].add(rank)

    def remove(self, name: str):
        self.pending.pop(name, None)
        self.warned.discard(name)

    def check(self) -> bool:
        """Returns True if the job should abort (stall past shutdown time)."""
        if not self.enabled:
            return False
        now = time.monotonic()
        if now - self.last_check < min(self.warning_time, 10.0):
            return False
        self.last_check = now
        abort = False
        for name, (t0, ready) in self.pending.items():
            age = now - t0
            if age > self.warning_time and name not in self.warned:
                missing = sorted(set(range(self.size)) - ready)
                logger.warning(
                    "One or more tensors were submitted to be reduced/gathered "
                    "but were not ready on all ranks for %.0fs. Stalled op: %s "
                    "[ready ranks: %s] [missing ranks: %s]",
                    age, name, sorted(ready), missing,
                )
                self.warned.add(name)
            if self.shutdown_time > 0 and age > self.shutdown_time:
                logger.error("Stall shutdown time exceeded for %s; aborting.", name)
                abort = True
        return abort
