"""Stall inspector: coordinator-side watchdog for stuck negotiations
(ref: horovod/common/stall_inspector.{h,cc}:30-96).

Warns when a tensor has been submitted by some ranks but is missing on
others for > HOROVOD_STALL_CHECK_TIME_SECONDS (default 60); optionally
aborts after HOROVOD_STALL_SHUTDOWN_TIME_SECONDS.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Set, Tuple

from ..utils import env as env_cfg
from ..utils.logging import get_logger

logger = get_logger()


class StallInspector:
    def __init__(self, size: int, registry=None):
        from ..common import telemetry

        if registry is None:
            registry = telemetry.default_registry()
        self._m_warnings = registry.counter(
            "horovod_stall_warnings_total",
            "Tensors that stalled past the warning threshold")
        self._m_aborts = registry.counter(
            "horovod_stall_aborts_total",
            "Stall-shutdown aborts issued by the coordinator")
        self.size = size
        self.enabled = not env_cfg.get_bool(env_cfg.STALL_CHECK_DISABLE, False)
        self.warning_time = env_cfg.get_float(
            env_cfg.STALL_CHECK_TIME, env_cfg.DEFAULT_STALL_WARNING_SECONDS
        )
        self.shutdown_time = env_cfg.get_float(env_cfg.STALL_SHUTDOWN_TIME, 0.0)
        self.last_check = time.monotonic()
        # tensor name -> (first-seen time, set of ready ranks)
        self.pending: Dict[str, Tuple[float, Set[int]]] = {}
        self.warned: Set[str] = set()

    def record(self, name: str, rank: int):
        now = time.monotonic()
        if name not in self.pending:
            self.pending[name] = (now, set())
        self.pending[name][1].add(rank)

    def remove(self, name: str):
        self.pending.pop(name, None)
        self.warned.discard(name)

    def check(self) -> Optional[str]:
        """Returns the abort reason when the job should shut down (a
        tensor stalled past HOROVOD_STALL_SHUTDOWN_TIME_SECONDS), else
        None. Truthy-on-abort keeps the old boolean contract; the reason
        string rides the coordinator's shutdown broadcast so EVERY
        rank's pending handles fail with the stall diagnosis — the same
        HorovodInternalError path a transport death takes — instead of a
        generic 'shut down' message only rank 0 can explain."""
        if not self.enabled:
            return None
        now = time.monotonic()
        if now - self.last_check < min(self.warning_time, 10.0):
            return None
        self.last_check = now
        abort: Optional[str] = None
        for name, (t0, ready) in self.pending.items():
            age = now - t0
            missing = sorted(set(range(self.size)) - ready)
            if age > self.warning_time and name not in self.warned:
                logger.warning(
                    "One or more tensors were submitted to be reduced/gathered "
                    "but were not ready on all ranks for %.0fs. Stalled op: %s "
                    "[ready ranks: %s] [missing ranks: %s]",
                    age, name, sorted(ready), missing,
                )
                self.warned.add(name)
                self._m_warnings.inc()
            if self.shutdown_time > 0 and age > self.shutdown_time:
                logger.error("Stall shutdown time exceeded for %s; aborting.", name)
                self._m_aborts.inc()
                if abort is None:
                    abort = (
                        f"stall shutdown: op {name} waited {age:.0f}s "
                        f"(> HOROVOD_STALL_SHUTDOWN_TIME_SECONDS="
                        f"{self.shutdown_time:.0f}) for rank(s) {missing}"
                    )
        return abort
