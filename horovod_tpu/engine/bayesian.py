"""Gaussian-process Bayesian optimization for the autotuner.

NumPy re-implementation of the reference's Eigen-based GP + expected-
improvement machinery (ref: horovod/common/optim/gaussian_process.{h,cc},
bayesian_optimization.{h,cc}): RBF-kernel GP posterior, EI acquisition,
next sample = argmax EI over the bounded box (random multistart instead
of the reference's LBFGS — same optimum in practice on 2-D boxes, no
third_party/lbfgs dependency).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class GaussianProcess:
    """RBF-kernel GP regression (ref: gaussian_process.h)."""

    def __init__(self, length_scale: float = 1.0, noise: float = 1e-6,
                 signal_var: float = 1.0):
        self.length_scale = length_scale
        self.noise = noise
        self.signal_var = signal_var
        self._x: Optional[np.ndarray] = None
        self._y: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.signal_var * np.exp(-0.5 * d2 / self.length_scale**2)

    def fit(self, x: np.ndarray, y: np.ndarray):
        self._x = np.atleast_2d(np.asarray(x, np.float64))
        self._y = np.asarray(y, np.float64).reshape(-1)
        k = self._kernel(self._x, self._x)
        k[np.diag_indices_from(k)] += self.noise
        self._chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, self._y)
        )

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean and std at x."""
        x = np.atleast_2d(np.asarray(x, np.float64))
        if self._x is None:
            return np.zeros(len(x)), np.full(len(x), np.sqrt(self.signal_var))
        ks = self._kernel(x, self._x)
        mean = ks @ self._alpha
        v = np.linalg.solve(self._chol, ks.T)
        var = np.maximum(
            self.signal_var - np.sum(v * v, axis=0), 1e-12
        )
        return mean, np.sqrt(var)


def expected_improvement(
    gp: GaussianProcess, x: np.ndarray, best_y: float, xi: float = 0.01
) -> np.ndarray:
    """(ref: bayesian_optimization.cc ExpectedImprovement)"""
    from math import erf, sqrt

    mean, std = gp.predict(x)
    imp = mean - best_y - xi
    z = imp / std
    cdf = 0.5 * (1.0 + np.vectorize(erf)(z / np.sqrt(2.0)))
    pdf = np.exp(-0.5 * z**2) / np.sqrt(2 * np.pi)
    ei = imp * cdf + std * pdf
    ei[std < 1e-9] = 0.0
    return ei


class BayesianOptimization:
    """Sequential model-based search over a bounded box
    (ref: bayesian_optimization.h — NextSample)."""

    def __init__(self, bounds: Sequence[Tuple[float, float]],
                 seed: int = 0, n_warmup: int = 3):
        self.bounds = np.asarray(bounds, np.float64)
        self.dim = len(bounds)
        self.rng = np.random.RandomState(seed)
        self.n_warmup = n_warmup
        self.xs: List[np.ndarray] = []
        self.ys: List[float] = []
        self.gp = GaussianProcess(length_scale=0.25)

    def _norm(self, x: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (x - lo) / (hi - lo)

    def _denorm(self, u: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return lo + u * (hi - lo)

    def register(self, x: Sequence[float], y: float):
        self.xs.append(self._norm(np.asarray(x, np.float64)))
        self.ys.append(float(y))

    def next_sample(self, n_candidates: int = 1000) -> np.ndarray:
        if len(self.xs) < self.n_warmup:
            # Space-filling warmup: fixed Halton-ish jittered grid.
            u = self.rng.rand(self.dim)
            return self._denorm(u)
        y = np.asarray(self.ys)
        # Normalize scores for GP conditioning.
        mu, sd = y.mean(), max(y.std(), 1e-9)
        self.gp.fit(np.stack(self.xs), (y - mu) / sd)
        cands = self.rng.rand(n_candidates, self.dim)
        ei = expected_improvement(self.gp, cands, float((y.max() - mu) / sd))
        return self._denorm(cands[int(np.argmax(ei))])

    @property
    def best(self) -> Tuple[Optional[np.ndarray], float]:
        if not self.ys:
            return None, -np.inf
        i = int(np.argmax(self.ys))
        return self._denorm(self.xs[i]), self.ys[i]
