"""Autotuner: online tuning of fusion threshold + cycle time.

(ref: horovod/common/parameter_manager.{h,cc}:163-228 — joint Bayesian
optimization of HOROVOD_FUSION_THRESHOLD and HOROVOD_CYCLE_TIME with a
GP surrogate, categorical toggles, bytes/sec scoring over sample windows
with warmup discard; best params broadcast to all ranks via
Controller::SynchronizeParameters, controller.cc:34-48. Enabled by
HOROVOD_AUTOTUNE, CSV log via HOROVOD_AUTOTUNE_LOG,
operations.cc:497-507.)

Only rank 0 tunes; every cycle the engine reports processed bytes, and
at window boundaries rank 0 either registers the score + proposes the
next sample (still tuning) or pins the best-seen parameters (done).
Parameter sync rides the existing control plane.
"""
from __future__ import annotations

import json
import time
from typing import List, Optional, Tuple

import numpy as np

from ..utils import env as env_cfg
from ..utils.logging import get_logger
from .bayesian import BayesianOptimization

logger = get_logger()

# Tuning box (ref: parameter_manager.cc bounds): fusion 0-64 MB on a
# log-ish scale via MB directly, cycle 1-25 ms.
FUSION_MB_BOUNDS = (1.0, 64.0)
CYCLE_MS_BOUNDS = (1.0, 25.0)


class ParameterManager:
    def __init__(
        self,
        is_coordinator: bool,
        enabled: Optional[bool] = None,
        warmup_samples: int = 1,
        cycles_per_sample: int = 10,
        max_samples: int = 20,
        log_path: Optional[str] = None,
    ):
        self.enabled = (
            env_cfg.get_bool(env_cfg.AUTOTUNE, False)
            if enabled is None else enabled
        )
        self.is_coordinator = is_coordinator
        self.warmup_samples = warmup_samples
        self.cycles_per_sample = cycles_per_sample
        self.max_samples = max_samples
        self.done = not self.enabled
        self._bo = BayesianOptimization(
            [FUSION_MB_BOUNDS, CYCLE_MS_BOUNDS]
        )
        self._samples = 0
        self._warmups_left = warmup_samples
        self._cycle_count = 0
        self._bytes = 0
        self._window_start = time.monotonic()
        self.fusion_threshold = env_cfg.fusion_threshold_bytes()
        self.cycle_time_ms = env_cfg.cycle_time_ms()
        self._log_path = log_path if log_path is not None else (
            env_cfg.get_str(env_cfg.AUTOTUNE_LOG) or None
        )
        if self.enabled and self.is_coordinator and self._log_path:
            with open(self._log_path, "w") as f:
                f.write("sample,fusion_mb,cycle_ms,score_bytes_per_sec\n")

    # ------------------------------------------------------------------
    def update(self, nbytes: int) -> bool:
        """Record one engine cycle's processed bytes. Returns True at a
        sync boundary — the caller must then run the collective
        parameter sync (coordinator serializes, workers apply) and
        re-read (fusion_threshold, cycle_time_ms).

        Cycle/window counting is driven by response cycles, which are
        identical on every rank, so all ranks reach boundaries together
        and the sync broadcast lines up (ref: ParameterManager::Update +
        RunLoopOnce autotune block, operations.cc:592-600)."""
        if self.done:
            return False
        self._bytes += nbytes
        self._cycle_count += 1
        if self._cycle_count < self.cycles_per_sample:
            return False
        elapsed = max(time.monotonic() - self._window_start, 1e-9)
        score = self._bytes / elapsed
        self._bytes = 0
        self._cycle_count = 0
        self._window_start = time.monotonic()
        if self._warmups_left > 0:
            # Discard warmup windows (ref: parameter_manager warmup);
            # identical countdown on every rank.
            self._warmups_left -= 1
            return False
        if self.is_coordinator:
            self._on_sample(score)
        return True

    def _on_sample(self, score: float) -> bool:
        self._bo.register(
            [self.fusion_threshold / (1024.0 * 1024.0), self.cycle_time_ms],
            score,
        )
        if self._log_path:
            with open(self._log_path, "a") as f:
                f.write(
                    f"{self._samples},"
                    f"{self.fusion_threshold / (1024.0 * 1024.0):.2f},"
                    f"{self.cycle_time_ms:.2f},{score:.1f}\n"
                )
        self._samples += 1
        if self._samples >= self.max_samples:
            best, best_y = self._bo.best
            self.fusion_threshold = int(best[0] * 1024 * 1024)
            self.cycle_time_ms = float(best[1])
            self.done = True
            logger.info(
                "autotune done: fusion=%.1fMB cycle=%.2fms (%.0f bytes/s)",
                best[0], best[1], best_y,
            )
            return True
        nxt = self._bo.next_sample()
        self.fusion_threshold = int(nxt[0] * 1024 * 1024)
        self.cycle_time_ms = float(nxt[1])
        return True

    # ------------------------------------------------------------------
    # Cross-rank parameter sync (ref: Controller::SynchronizeParameters).
    def serialize(self) -> bytes:
        return json.dumps({
            "fusion_threshold": self.fusion_threshold,
            "cycle_time_ms": self.cycle_time_ms,
            "done": self.done,
        }).encode()

    def apply(self, payload: bytes):
        d = json.loads(payload.decode())
        self.fusion_threshold = int(d["fusion_threshold"])
        self.cycle_time_ms = float(d["cycle_time_ms"])
        self.done = bool(d["done"])
