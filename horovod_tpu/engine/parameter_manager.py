"""Autotuner: online tuning of fusion threshold + cycle time.

(ref: horovod/common/parameter_manager.{h,cc}:163-228 — joint Bayesian
optimization of HOROVOD_FUSION_THRESHOLD and HOROVOD_CYCLE_TIME with a
GP surrogate, categorical toggles, bytes/sec scoring over sample windows
with warmup discard; best params broadcast to all ranks via
Controller::SynchronizeParameters, controller.cc:34-48. Enabled by
HOROVOD_AUTOTUNE, CSV log via HOROVOD_AUTOTUNE_LOG,
operations.cc:497-507.)

Categorical knobs (ref: parameter_manager.h:163-228 tunes
hierarchical_allreduce and cache_enabled as CategoricalParameterChains):
the tuner enumerates (hierarchical, cache) arms round-robin, each arm
carrying its own GP over the continuous (fusion, cycle) box; the final
pick is the best-scoring (arm, fusion, cycle) triple seen.

Only rank 0 tunes; every cycle the engine reports processed bytes, and
at window boundaries rank 0 either registers the score + proposes the
next sample (still tuning) or pins the best-seen parameters (done).
Parameter sync rides the existing control plane.
"""
from __future__ import annotations

import json
import time
from typing import List, Optional, Tuple

import numpy as np

from ..utils import env as env_cfg
from ..utils.logging import get_logger
from .bayesian import BayesianOptimization

logger = get_logger()

# Tuning box (ref: parameter_manager.cc bounds): fusion 0-64 MB on a
# log-ish scale via MB directly, cycle 1-25 ms.
FUSION_MB_BOUNDS = (1.0, 64.0)
CYCLE_MS_BOUNDS = (1.0, 25.0)


class ParameterManager:
    def __init__(
        self,
        is_coordinator: bool,
        enabled: Optional[bool] = None,
        warmup_samples: int = 1,
        cycles_per_sample: int = 10,
        max_samples: int = 20,
        log_path: Optional[str] = None,
        tune_hierarchical: bool = False,
        tune_cache: bool = True,
        registry=None,
    ):
        from ..common import telemetry

        if registry is None:
            registry = telemetry.default_registry()
        self._m_samples = registry.counter(
            "horovod_autotune_samples_total",
            "Autotune sample windows scored (coordinator)")
        self._m_score = registry.gauge(
            "horovod_autotune_score_bytes_per_second",
            "Last autotune window score")
        self._m_fusion = registry.gauge(
            "horovod_fusion_threshold_bytes", "Active fusion threshold")
        self._m_cycle_ms = registry.gauge(
            "horovod_cycle_time_ms", "Active engine cycle time")
        self._m_done = registry.gauge(
            "horovod_autotune_done",
            "1 once tuning converged (or autotune is off)")
        self.enabled = (
            env_cfg.get_bool(env_cfg.AUTOTUNE, False)
            if enabled is None else enabled
        )
        self.is_coordinator = is_coordinator
        self.warmup_samples = warmup_samples
        self.cycles_per_sample = cycles_per_sample
        self.max_samples = max_samples
        self.done = not self.enabled
        self._samples = 0
        self._warmups_left = warmup_samples
        self._cycle_count = 0
        self._bytes = 0
        self._window_start = time.monotonic()
        self.fusion_threshold = env_cfg.fusion_threshold_bytes()
        self.cycle_time_ms = env_cfg.cycle_time_ms()
        self.hierarchical = (
            env_cfg.hierarchical_allreduce_setting() != "off"
        )
        self.cache_enabled = env_cfg.cache_enabled()
        # Categorical arms: (hierarchical, cache_enabled) combos, each
        # with its own GP over the continuous box.
        self._tune_cache = tune_cache
        self._build_arms(tune_hierarchical)
        self._m_fusion.set(self.fusion_threshold)
        self._m_cycle_ms.set(self.cycle_time_ms)
        self._m_done.set(1.0 if self.done else 0.0)
        self._log_path = log_path if log_path is not None else (
            env_cfg.get_str(env_cfg.AUTOTUNE_LOG) or None
        )
        if self.enabled and self.is_coordinator and self._log_path:
            with open(self._log_path, "w") as f:
                f.write(
                    "sample,fusion_mb,cycle_ms,hierarchical,cache,"
                    "score_bytes_per_sec\n"
                )

    def _build_arms(self, tune_hierarchical: bool):
        hs = (False, True) if tune_hierarchical else (False,)
        cs = (True, False) if self._tune_cache else (True,)
        self._arms: List[Tuple[bool, bool]] = [
            (h, c) for h in hs for c in cs
        ]
        self._arm_bo = [
            BayesianOptimization([FUSION_MB_BOUNDS, CYCLE_MS_BOUNDS])
            for _ in self._arms
        ]
        # Start on the arm matching the state the first window actually
        # runs with (the engine's env-derived toggles), so sample 0's
        # score is credited to the right categorical combo. If that
        # state isn't a tunable arm (e.g. env asked hierarchical but the
        # topology vetoed it), clamp to arm 0 — which is what the engine
        # will run.
        state = (self.hierarchical, self.cache_enabled)
        if state in self._arms:
            self._arm_idx = self._arms.index(state)
        else:
            self._arm_idx = 0
            self.hierarchical, self.cache_enabled = self._arms[0]

    def set_tune_hierarchical(self, eligible: bool):
        """Rebuild the arm set once topology validity is known (the
        engine agrees it collectively after init). Must be called before
        the first sample window closes; no samples are lost because
        windows only open once response cycles flow."""
        if self._samples == 0:
            self._build_arms(eligible)

    # ------------------------------------------------------------------
    def update(self, nbytes: int) -> bool:
        """Record one engine cycle's processed bytes. Returns True at a
        sync boundary — the caller must then run the collective
        parameter sync (coordinator serializes, workers apply) and
        re-read (fusion_threshold, cycle_time_ms, hierarchical,
        cache_enabled).

        Cycle/window counting is driven by response cycles, which are
        identical on every rank, so all ranks reach boundaries together
        and the sync broadcast lines up (ref: ParameterManager::Update +
        RunLoopOnce autotune block, operations.cc:592-600)."""
        if self.done:
            return False
        self._bytes += nbytes
        self._cycle_count += 1
        if self._cycle_count < self.cycles_per_sample:
            return False
        elapsed = max(time.monotonic() - self._window_start, 1e-9)
        score = self._bytes / elapsed
        self._bytes = 0
        self._cycle_count = 0
        self._window_start = time.monotonic()
        if self._warmups_left > 0:
            # Discard warmup windows (ref: parameter_manager warmup);
            # identical countdown on every rank.
            self._warmups_left -= 1
            return False
        if self.is_coordinator:
            self._on_sample(score)
        return True

    def _sync_gauges(self):
        self._m_fusion.set(self.fusion_threshold)
        self._m_cycle_ms.set(self.cycle_time_ms)
        self._m_done.set(1.0 if self.done else 0.0)

    def _on_sample(self, score: float) -> bool:
        self._m_samples.inc()
        self._m_score.set(score)
        self._arm_bo[self._arm_idx].register(
            [self.fusion_threshold / (1024.0 * 1024.0), self.cycle_time_ms],
            score,
        )
        if self._log_path:
            with open(self._log_path, "a") as f:
                f.write(
                    f"{self._samples},"
                    f"{self.fusion_threshold / (1024.0 * 1024.0):.2f},"
                    f"{self.cycle_time_ms:.2f},"
                    f"{int(self.hierarchical)},{int(self.cache_enabled)},"
                    f"{score:.1f}\n"
                )
        self._samples += 1
        if self._samples >= self.max_samples:
            best_arm, best_x, best_y = 0, None, -np.inf
            for i, bo in enumerate(self._arm_bo):
                x, y = bo.best  # (None, -inf) when the arm is unsampled
                if x is not None and y > best_y:
                    best_arm, best_x, best_y = i, x, y
            if best_x is not None:
                self.fusion_threshold = int(best_x[0] * 1024 * 1024)
                self.cycle_time_ms = float(best_x[1])
                self.hierarchical, self.cache_enabled = self._arms[best_arm]
            self.done = True
            self._sync_gauges()
            logger.info(
                "autotune done: fusion=%.1fMB cycle=%.2fms hier=%s cache=%s "
                "(%.0f bytes/s)",
                self.fusion_threshold / 1048576.0, self.cycle_time_ms,
                self.hierarchical, self.cache_enabled, best_y,
            )
            return True
        # Rotate to the next arm and draw its next continuous sample.
        self._arm_idx = (self._arm_idx + 1) % len(self._arms)
        self.hierarchical, self.cache_enabled = self._arms[self._arm_idx]
        nxt = self._arm_bo[self._arm_idx].next_sample()
        self.fusion_threshold = int(nxt[0] * 1024 * 1024)
        self.cycle_time_ms = float(nxt[1])
        self._sync_gauges()
        return True

    # ------------------------------------------------------------------
    # Cross-rank parameter sync (ref: Controller::SynchronizeParameters).
    def serialize(self) -> bytes:
        return json.dumps({
            "fusion_threshold": self.fusion_threshold,
            "cycle_time_ms": self.cycle_time_ms,
            "hierarchical": self.hierarchical,
            "cache_enabled": self.cache_enabled,
            "done": self.done,
        }).encode()

    def apply(self, payload: bytes):
        d = json.loads(payload.decode())
        self.fusion_threshold = int(d["fusion_threshold"])
        self.cycle_time_ms = float(d["cycle_time_ms"])
        self.hierarchical = bool(d.get("hierarchical", False))
        self.cache_enabled = bool(d.get("cache_enabled", True))
        self.done = bool(d["done"])
        self._sync_gauges()
