"""Chrome-tracing timeline writer.

Re-design of the reference timeline (ref: horovod/common/timeline.{h,cc}
:47-126): per-tensor lanes with a NEGOTIATING phase (per-rank ready
ticks), then the op phase with nested activities (QUEUE,
MEMCPY_IN_FUSION_BUFFER, <BACKEND>_ALLREDUCE, ...). Records are pushed to
a writer thread through a queue so the hot path never blocks on file IO
(the reference uses a boost lock-free SPSC ring; a stdlib queue fills the
same role at Python speeds). Enabled by HOROVOD_TIMELINE=<file> and
written by the coordinator only (ref: operations.cc:416-429).
"""
from __future__ import annotations

import json
import queue
import threading
import time
from typing import Dict, Optional

from ..utils import clock
from ..utils import env as env_cfg
from ..utils.logging import get_logger

logger = get_logger()

# Activity names (ref: horovod/common/common.h:32-62)
QUEUE = "QUEUE"
MEMCPY_IN_FUSION_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
MEMCPY_OUT_FUSION_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"
NEGOTIATE = "NEGOTIATE"


class Timeline:
    def __init__(self, filename: Optional[str] = None, use_env: bool = True,
                 registry=None, queue_size: int = 1 << 20):
        from ..common import telemetry

        # use_env=False on non-coordinator ranks: only rank 0 writes
        # (ref: operations.cc:416-429).
        if filename is None and use_env:
            filename = env_cfg.get_str(env_cfg.TIMELINE) or None
        self.filename = filename
        self.enabled = bool(self.filename)
        self.mark_cycles = env_cfg.get_bool(env_cfg.TIMELINE_MARK_CYCLES, False)
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        # Multi-writer: the background loop (negotiation phases) and the
        # channel executors (op phases) emit concurrently; lane-id
        # allocation is the only read-modify-write and takes the lock.
        self._tids: Dict[str, int] = {}
        self._tid_lock = threading.Lock()
        self._writer: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # A full writer queue drops events (the hot path must never block
        # on file IO) — but silently losing trace data made every
        # truncated timeline look complete. Count the drops, shout once
        # — through the tracing plane's shared drop counter, so one
        # metric covers every trace output (docs/tracing.md).
        self._m_dropped = (registry or telemetry.default_registry()).counter(
            "horovod_trace_events_dropped_total",
            "Trace events lost before reaching an output (flight-"
            "recorder ring overwrites, timeline writer-queue drops)",
            labels={"source": "timeline"})
        self._warned_drop = False
        if self.enabled:
            self._writer = threading.Thread(
                target=self._write_loop, name="hvd-timeline", daemon=True
            )
            self._writer.start()

    def _ts(self) -> float:
        # Shared process anchor (utils/clock): this file's ts axis now
        # lines up with the tracing plane's spans and — via the wall-
        # clock identity in the metadata event — with mesh_timeline.py
        # device lanes when spliced side by side.
        return clock.trace_us(clock.mono_ns())  # microseconds

    def _tid(self, tensor_name: str) -> int:
        with self._tid_lock:
            tid = self._tids.get(tensor_name)
            if tid is None:
                tid = self._tids[tensor_name] = len(self._tids) + 1
            return tid

    def _emit(self, ev: dict):
        if not self.enabled:
            return
        try:
            self._q.put_nowait(ev)
        except queue.Full:
            self._m_dropped.inc()
            if not self._warned_drop:
                self._warned_drop = True
                logger.warning(
                    "timeline writer queue is full; dropping events (the "
                    "trace will have gaps — see "
                    'horovod_trace_events_dropped_total{source="timeline"})')

    # -- per-tensor state machine (ref: timeline.h:81-126) --------------
    def negotiate_start(self, name: str, op_name: str):
        self._emit({"ph": "B", "name": f"NEGOTIATE_{op_name}", "pid": 0,
                    "tid": self._tid(name), "ts": self._ts()})

    def negotiate_rank_ready(self, name: str, rank: int):
        self._emit({"ph": "i", "name": str(rank), "pid": 0,
                    "tid": self._tid(name), "ts": self._ts(), "s": "t"})

    def negotiate_end(self, name: str, op_name: str):
        self._emit({"ph": "E", "name": f"NEGOTIATE_{op_name}", "pid": 0,
                    "tid": self._tid(name), "ts": self._ts()})

    def start(self, name: str, op_name: str):
        self._emit({"ph": "B", "name": op_name, "pid": 0,
                    "tid": self._tid(name), "ts": self._ts()})

    def activity_start(self, name: str, activity: str):
        self._emit({"ph": "B", "name": activity, "pid": 0,
                    "tid": self._tid(name), "ts": self._ts()})

    def activity_end(self, name: str):
        self._emit({"ph": "E", "pid": 0, "tid": self._tid(name), "ts": self._ts()})

    def activity(self, name: str, activity: str):
        """Context manager: the E event fires even when the op raises,
        keeping B/E balanced on the lane (an unbalanced lane nests every
        later event under the dangling phase in the trace viewer)."""
        import contextlib

        @contextlib.contextmanager
        def _span():
            self.activity_start(name, activity)
            try:
                yield
            finally:
                self.activity_end(name)

        return _span()

    def end(self, name: str, op_name: str):
        self._emit({"ph": "E", "name": op_name, "pid": 0,
                    "tid": self._tid(name), "ts": self._ts()})

    def mark_cycle(self):
        if self.mark_cycles:
            self._emit({"ph": "i", "name": "CYCLE", "pid": 0, "tid": 0,
                        "ts": self._ts(), "s": "g"})

    # -------------------------------------------------------------------
    def _write_loop(self):
        with open(self.filename, "w") as f:
            f.write("[\n")
            # Clock-anchor metadata event first: the wall-clock identity
            # of this file's t=0, so offline tools can splice it against
            # the mesh timeline's device lanes (or another process's
            # host lanes) on a common axis.
            f.write(json.dumps({"ph": "M", "name": "horovod_clock",
                                "pid": 0, "tid": 0,
                                "args": clock.anchor_meta()}))
            first = False
            while not self._stop.is_set() or not self._q.empty():
                try:
                    ev = self._q.get(timeout=0.1)
                except queue.Empty:
                    continue
                if not first:
                    f.write(",\n")
                f.write(json.dumps(ev))
                first = False
                f.flush()
            f.write("\n]\n")

    def shutdown(self):
        if self.enabled and self._writer is not None:
            # Disable BEFORE draining so no new events race the flush,
            # then give the writer time proportional to the backlog
            # instead of a flat 5s that abandons buffered events of a
            # long run mid-file.
            self.enabled = False
            self._stop.set()
            deadline = time.monotonic() + 30.0
            while self._writer.is_alive() and time.monotonic() < deadline:
                self._writer.join(timeout=1.0)
            if self._writer.is_alive():
                logger.warning(
                    "timeline writer did not drain %d buffered events "
                    "before shutdown", self._q.qsize())
            dropped = self._m_dropped.value
            if dropped:
                logger.warning(
                    "timeline dropped %d events during the run (writer "
                    "queue full); the trace has gaps", dropped)
