"""Device-side timeline for the traced/GSPMD path.

The eager engine's `Timeline` covers host-side negotiation and backend
activities; under `jit` the collectives are compiled into the XLA
module, so their timings only exist device-side. The reference has the
same split — its GPU ops record CUDA events into the timeline after the
fact (ref: horovod/common/ops/gpu_operations.h:110-118). Here the
device record comes from the XLA profiler: `MeshTimeline.capture()`
wraps any traced-step region, then splices the profiler's device lanes
into one Chrome-trace file, with the collective ops (all-reduce /
all-gather / all-to-all / collective-permute / reduce-scatter) pulled
onto a dedicated "ICI collectives" lane so step compute and
communication read side-by-side in chrome://tracing or Perfetto.

Usage::

    tl = MeshTimeline("mesh_timeline.json")   # or HOROVOD_TIMELINE env
    with tl.capture():
        for _ in range(3):
            state, loss = step(state, batch)
        jax.block_until_ready(loss)
    # mesh_timeline.json now holds device lanes + collective lane.
"""
from __future__ import annotations

import os
import re
import shutil
import tempfile
from contextlib import contextmanager
from typing import List, Optional

from ..utils import chrome_trace, clock
from ..utils import env as env_cfg

# XLA op-name fragments that identify cross-device communication.
_COLLECTIVE_PAT = re.compile(
    r"all-reduce|all-gather|all-to-all|collective-permute|reduce-scatter"
    r"|psum|ppermute|collective-broadcast",
    re.IGNORECASE,
)
_COLLECTIVE_LANE_PID = 999


class MeshTimeline:
    def __init__(self, output_path: Optional[str] = None,
                 use_env: bool = True):
        if output_path is None and use_env:
            base = env_cfg.get_str(env_cfg.TIMELINE) or None
            if base:
                root, ext = os.path.splitext(base)
                output_path = f"{root}.mesh{ext or '.json'}"
        self.output_path = output_path
        self.enabled = bool(output_path)

    @contextmanager
    def capture(self):
        """Profile the enclosed traced-step region and write the spliced
        Chrome trace on exit. No-op (still yields) when disabled."""
        if not self.enabled:
            yield
            return
        import jax

        tmp = tempfile.mkdtemp(prefix="hvd_mesh_tl_")
        jax.profiler.start_trace(tmp)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
            try:
                self._splice(tmp)
            finally:
                shutil.rmtree(tmp, ignore_errors=True)

    # ------------------------------------------------------------------
    def _splice(self, profile_dir: str):
        # Shared glob/gzip/parse helper (utils/chrome_trace) — the same
        # reader scripts/profile_step.py and the tracing plane's
        # analyzers use.
        events = chrome_trace.load_profiler_events(profile_dir)
        if events is None:
            return
        out: List[dict] = []
        device_pids = set()
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                pname = (ev.get("args") or {}).get("name", "")
                if "host" not in pname.lower():
                    device_pids.add(ev["pid"])
                out.append(ev)
        for ev in events:
            if ev.get("ph") == "M":
                continue
            if ev.get("pid") in device_pids:
                out.append(ev)
                # Duplicate communication ops onto the dedicated lane.
                if ev.get("ph") == "X" and _COLLECTIVE_PAT.search(
                        ev.get("name", "")):
                    c = dict(ev)
                    c["pid"] = _COLLECTIVE_LANE_PID
                    c["tid"] = 0
                    out.append(c)
        out.append({"ph": "M", "name": "process_name",
                    "pid": _COLLECTIVE_LANE_PID,
                    "args": {"name": "ICI collectives"}})
        # The wall-clock identity of this process's host-trace origin
        # rides along so the host timeline (engine/timeline.py, same
        # anchor) can be laid next to these device lanes offline.
        chrome_trace.write_trace(
            self.output_path, out,
            metadata={"horovod_clock": clock.anchor_meta()})
