"""Elastic state objects: in-memory replicated checkpoints.

(ref: horovod/common/elastic.py:95-145 State/ObjectState;
horovod/torch/elastic.py:51-84 TorchState — deepcopy save/restore +
broadcast sync.)

JAX pytrees make this clean: `save` keeps a host copy of the tree,
`restore` reinstates it, `sync` broadcasts rank 0's tree so a newly
added worker starts consistent.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from ..common import basics
from ..common.functions import broadcast_object, broadcast_parameters


class State:
    """Base elastic state (ref: common/elastic.py:95-145)."""

    def __init__(self, **kwargs):
        self._reset_callbacks: List[Callable[[], None]] = []
        self._host_messages: List[Any] = []
        self._last_updated_timestamp = 0

    def register_reset_callbacks(self, callbacks):
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self._host_messages.clear()
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def on_hosts_updated(self, timestamp, update_res):
        self._host_messages.append((timestamp, update_res))

    def commit(self):
        """Save + check for pending host updates
        (ref: common/elastic.py:60-71). With a checkpoint manager
        attached, the freshly committed snapshot is also offered to the
        durability plane — BEFORE the host-update check, which may
        raise HostsUpdatedInterrupt (the snapshot must not be lost to
        the reset)."""
        self.save()
        mgr = getattr(self, "_checkpoint_manager", None)
        if mgr is not None:
            mgr.maybe_save(self)
        # Goodput plane (docs/goodput.md): a commit is a step boundary
        # (the lowest-priority demarcation source) and advances the
        # committed-step cursor replay accounting rewinds to. BEFORE
        # the host-update check for the same reason the snapshot is:
        # a HostsUpdatedInterrupt must not lose the committed step.
        from ..common import drain, goodput

        goodput.note_commit()
        # Drain plane (docs/fault_tolerance.md "Announced preemption"):
        # a pending preemption notice anywhere in the world completes
        # here — all ranks force this commit durable together and the
        # draining rank departs via WorkerPreempted. BEFORE the
        # host-update check: the drain must hand off against the commit
        # that just landed, not be lost to a reset.
        drain.commit_barrier(self)
        self.check_host_updates()

    def check_host_updates(self):
        """Raise HostsUpdatedInterrupt on all ranks together
        (ref: common/elastic.py:73-93). The broadcast runs UNCONDITIONALLY
        every commit — notifications arrive per-worker and asynchronously,
        so an early-out on ranks without a queued message would leave the
        notified ranks alone inside the collective, hanging them. Rank 0's
        view decides; a message rank 0 hasn't seen yet fires on a later
        commit."""
        from ..common.exceptions import HostsUpdatedInterrupt

        prev = last = self._last_updated_timestamp
        res = 0
        for ts, update in self._host_messages:
            if ts > last:
                last = ts
            # OR-accumulate across every queued message (ref:
            # common/elastic.py `all_update |= update`): an ADDED
            # followed by a REMOVED in one window must yield MIXED so
            # sync is not skipped while a new worker waits in sync().
            if ts > prev:
                res |= update
        self._host_messages.clear()
        prev, last, res = broadcast_object(
            (prev, last, res), root_rank=0, name="host_update_ts"
        )
        self._last_updated_timestamp = last
        if last > prev:
            # Sync is skippable only for removal-only updates: nobody new
            # needs the state (ref: common/elastic.py HostUpdateResult —
            # `all_update == HostUpdateResult.removed`). An ADDED update
            # must sync or the joining worker hangs in state.sync().
            from ..runner.elastic.discovery import HostUpdateResult

            raise HostsUpdatedInterrupt(
                skip_sync=(res == HostUpdateResult.REMOVED)
            )

    def set_checkpoint_manager(self, manager):
        """Attach the durability plane (common/checkpoint.py): every
        ``commit()`` then also feeds the manager, which checkpoints the
        committed snapshot to shared storage every N commits. The
        elastic run loop wires this from HOROVOD_CHECKPOINT_DIR
        (docs/checkpoint.md)."""
        self._checkpoint_manager = manager

    # subclass interface
    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError

    def reset(self):
        pass

    # -- durability hooks (common/checkpoint.py) -----------------------
    # The checkpoint payload is the last *committed* snapshot — the
    # same rollback point `restore()` uses — never the live attributes
    # (which training mutates while the background writer runs).
    def supports_durability(self) -> bool:
        """Whether this state implements the checkpoint hooks. The
        elastic run loop checks this before wiring a manager: a state
        without hooks must not commit (empty) checkpoints it could
        never load back."""
        return False

    def checkpoint_objects(self) -> dict:
        return {}

    def checkpoint_trees(self) -> dict:
        """{attr: flat leaf list} of the committed pytree snapshots."""
        return {}

    def load_checkpoint(self, objects: dict, trees: dict):
        raise NotImplementedError(
            "this State subclass does not support durable checkpoints")


class ObjectState(State):
    """State of picklable attributes (ref: common/elastic.py ObjectState)."""

    def __init__(self, **kwargs):
        super().__init__()
        self._saved: Dict[str, Any] = {}
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._attrs = list(kwargs.keys())
        self.save()

    def save(self):
        self._saved = {k: copy.deepcopy(getattr(self, k)) for k in self._attrs}

    def restore(self):
        for k, v in self._saved.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self):
        synced = broadcast_object(
            {k: getattr(self, k) for k in self._attrs}, root_rank=0,
            name="object_state",
        )
        for k, v in synced.items():
            setattr(self, k, v)
        self.save()

    # -- durability hooks (common/checkpoint.py) -----------------------
    def supports_durability(self) -> bool:
        return True

    def checkpoint_objects(self) -> dict:
        # `_saved` was deep-copied at save() and is REBOUND (never
        # mutated) by the next save(), so the background writer can
        # pickle this dict while training commits ahead.
        return self._saved

    def load_checkpoint(self, objects: dict, trees: dict):
        if trees:
            raise ValueError(
                "checkpoint holds pytrees but this state is a plain "
                "ObjectState; restore into a JaxState")
        for k, v in objects.items():
            setattr(self, k, copy.deepcopy(v))
            if k not in self._attrs:
                self._attrs.append(k)
        self.save()


class JaxState(ObjectState):
    """Elastic state holding JAX pytrees (params/opt_state) plus scalars
    — the JAX analogue of TorchState (ref: torch/elastic.py:51-84).

    Pytree attributes are synced with tensor broadcasts (not pickle), so
    large weights ride the collective data plane.
    """

    def __init__(self, params=None, opt_state=None, **kwargs):
        self.params = params
        self.opt_state = opt_state
        self._tree_attrs = ["params", "opt_state"]
        super().__init__(**kwargs)

    def save(self):
        super().save()
        # Host-copy every leaf. np.asarray materializes device arrays
        # but ALIASES leaves that are already np.ndarrays — and an
        # aliased snapshot is silently corrupted by in-place training
        # updates (a numpy optimizer step), poisoning both the
        # rollback point and whatever the background checkpoint writer
        # is pickling. A jax.Array is immutable, so its asarray host
        # view is safe to reference.
        self._saved_trees = {
            k: jax.tree.map(
                lambda a: a.copy() if isinstance(a, np.ndarray)
                else np.asarray(a),
                getattr(self, k))
            for k in self._tree_attrs
            if getattr(self, k) is not None
        }

    def restore(self):
        # COPY the snapshot out — never hand back the saved arrays
        # themselves. The old identity map (`lambda a: a`) aliased the
        # restored attributes to `_saved_trees`, so post-restore
        # in-place mutation (a numpy optimizer step, a donated buffer)
        # silently corrupted the rollback snapshot AND any checkpoint
        # writer still serializing it: a second restore() then yielded
        # the mutated values, not the committed ones.
        super().restore()
        for k, v in getattr(self, "_saved_trees", {}).items():
            setattr(self, k, jax.tree.map(np.copy, v))

    def sync(self):
        for k in self._tree_attrs:
            v = getattr(self, k)
            if v is not None:
                setattr(self, k, broadcast_parameters(v, root_rank=0))
        super().sync()

    # -- durability hooks (common/checkpoint.py) -----------------------
    def checkpoint_trees(self) -> dict:
        # Leaves of the committed host-side snapshot, in deterministic
        # (tree-flatten) order. The arrays are the host copies save()
        # made; save() rebinds `_saved_trees` rather than mutating it,
        # so the background writer reads a stable view.
        return {
            k: jax.tree.leaves(v)
            for k, v in getattr(self, "_saved_trees", {}).items()
        }

    def load_checkpoint(self, objects: dict, trees: dict):
        """Reassemble restored leaves against the LIVE state's tree
        structure (the restarted job constructed the same model), so a
        checkpoint written at any world size loads at any other."""
        for k, leaves in trees.items():
            cur = getattr(self, k, None)
            if cur is None:
                raise ValueError(
                    f"checkpoint holds pytree {k!r} but the restarted "
                    f"state has no structure for it; construct the "
                    f"state with {k}= before restoring")
            treedef = jax.tree.structure(cur)
            if treedef.num_leaves != len(leaves):
                raise ValueError(
                    f"checkpoint pytree {k!r} has {len(leaves)} leaves "
                    f"but the live state expects {treedef.num_leaves}; "
                    "the model structure changed since the checkpoint")
            setattr(self, k, jax.tree.unflatten(treedef, leaves))
        super().load_checkpoint(objects, {})


# Alias for users coming from flax TrainState-centric code.
TrainStateState = JaxState
