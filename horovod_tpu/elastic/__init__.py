"""Elastic training: worker-side state + fault-tolerant run loop.

(ref: horovod/common/elastic.py:1-168 — State/ObjectState/run_fn;
horovod/torch/elastic.py:51-84 TorchState.)

Worker loop semantics (ref: common/elastic.py:147-168):
    loop { state.sync(); train(state);
           except HorovodInternalError -> state.restore();
           except HostsUpdatedInterrupt -> (commit is still valid);
           reset(): hvd.shutdown()+hvd.init(); state.on_reset() }
"""
from ..common.checkpoint import CheckpointManager
from .state import State, ObjectState, JaxState, TrainStateState
from .run import run, run_fn

__all__ = ["State", "ObjectState", "JaxState", "TrainStateState", "run",
           "run_fn", "CheckpointManager"]
