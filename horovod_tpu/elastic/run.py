"""Elastic run loop (ref: horovod/common/elastic.py:115-168 run_fn)."""
from __future__ import annotations

import functools
from typing import Callable

from ..common import basics
from ..common.exceptions import HorovodInternalError, HostsUpdatedInterrupt
from ..utils.logging import get_logger
from .state import State

logger = get_logger()


def _reset():
    """Full re-initialization with the new topology
    (ref: common/elastic.py reset → hvd.shutdown()+hvd.init();
    rank/size are re-read from the rendezvous-updated env)."""
    from ..backend import elastic_env

    basics.shutdown()
    elastic_env.refresh_topology_from_rendezvous()
    basics.init()


def run(func: Callable) -> Callable:
    """Decorator: `@hvd.elastic.run` (ref: common/elastic.py:115-130)."""

    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        return run_fn(func, state, *args, **kwargs)

    return wrapper


def run_fn(func: Callable, state: State, *args, **kwargs):
    """(ref: common/elastic.py:133-168)"""
    from ..backend.elastic_env import notification_manager

    notification_manager.init()
    notification_manager.register_listener(state)
    skip_sync = False
    try:
        while True:
            if not skip_sync:
                state.sync()
            try:
                return func(state, *args, **kwargs)
            except HorovodInternalError:
                logger.warning("collective failure; restoring last commit")
                state.restore()
                skip_sync = False
            except HostsUpdatedInterrupt as e:
                logger.info("hosts updated; re-initializing")
                skip_sync = e.skip_sync
            _reset()
            state.on_reset()
    finally:
        notification_manager.remove_listener(state)
