"""Elastic run loop (ref: horovod/common/elastic.py:115-168 run_fn)."""
from __future__ import annotations

import functools
from typing import Callable

from ..common import basics, drain, goodput, telemetry
from ..common import events as events_mod
from ..common.exceptions import HorovodInternalError, HostsUpdatedInterrupt
from ..utils.logging import get_logger
from .state import State

logger = get_logger()

# Elastic lifecycle counters (docs/metrics.md): a fleet whose
# resets_total climbs while restores_total stays flat is churning on
# topology changes; the reverse means workers keep dying mid-step.
_m_resets = telemetry.counter(
    "horovod_elastic_resets_total",
    "Full shutdown+init cycles taken by the elastic run loop")
_m_restores = telemetry.counter(
    "horovod_elastic_restores_total",
    "State restores after a collective failure (worker death)")
_m_host_updates = telemetry.counter(
    "horovod_elastic_host_updates_total",
    "Host add/remove notifications that interrupted training")


def _reset():
    """Full re-initialization with the new topology
    (ref: common/elastic.py reset → hvd.shutdown()+hvd.init();
    rank/size are re-read from the rendezvous-updated env)."""
    from ..backend import elastic_env

    _m_resets.inc()
    events_mod.emit(events_mod.ELASTIC_RESET)
    # shutdown() also stops the notification server (it must not leak
    # across resets); re-init it after the new topology lands so this
    # worker re-registers its endpoint — under the NEW epoch's env —
    # and keeps receiving host updates.
    basics.shutdown()
    elastic_env.refresh_topology_from_rendezvous()
    # init() re-sets the horovod_world_size gauge, so shrink/grow
    # history shows up next to the reset count.
    basics.init()
    elastic_env.notification_manager.init()


def run(func: Callable) -> Callable:
    """Decorator: `@hvd.elastic.run` (ref: common/elastic.py:115-130)."""

    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        return run_fn(func, state, *args, **kwargs)

    return wrapper


def run_fn(func: Callable, state: State, *args, **kwargs):
    """(ref: common/elastic.py:133-168)

    With ``HOROVOD_CHECKPOINT_DIR`` set, the durability plane
    (docs/checkpoint.md) wraps the loop: the newest complete durable
    checkpoint is restored into `state` BEFORE the first sync — so a
    job whose every rank died resumes at the last committed step — and
    every ``state.commit()`` thereafter feeds the background shard
    writer. The restore happens identically on every rank (all shards
    are read from shared storage), so the first ``state.sync()``
    broadcast confirms rather than repairs."""
    from ..backend.elastic_env import notification_manager
    from ..common import checkpoint

    notification_manager.init()
    notification_manager.register_listener(state)
    # Drain plane (docs/fault_tolerance.md "Announced preemption"):
    # managed mode on every rank alike — a preemption notice now drains
    # at a commit boundary (state.py commit_barrier) instead of exiting
    # from the handler.
    drain.coordinator.install(managed=True)
    ckpt_mgr = checkpoint.manager_from_env()
    if ckpt_mgr is not None and not state.supports_durability():
        # A state without the hooks would commit (empty) checkpoints it
        # could never load back — crashing a RESTART instead of this
        # run. Loudly off is strictly better.
        logger.warning(
            "HOROVOD_CHECKPOINT_DIR is set but %s implements no "
            "durability hooks (checkpoint_objects/checkpoint_trees/"
            "load_checkpoint); durable checkpointing is disabled",
            type(state).__name__)
        ckpt_mgr = None
    if ckpt_mgr is not None:
        checkpoint.set_current(ckpt_mgr)
        state.set_checkpoint_manager(ckpt_mgr)
        restored = ckpt_mgr.restore_latest(state)
        if restored is not None:
            logger.info("resuming from durable checkpoint at step %d",
                        restored)
            # Goodput (docs/goodput.md): the durable ledger stamp knows
            # how far the previous lifetime got; everything between the
            # restored step and that cursor will be re-executed —
            # replay badput, counted once here.
            goodput.note_restore(restored)
    skip_sync = False
    try:
        while True:
            if not skip_sync:
                state.sync()
            # Training is live again: close any open disruption window
            # into the restart-badput bucket (no-op on the first pass).
            goodput.disruption_end()
            try:
                return func(state, *args, **kwargs)
            except HorovodInternalError:
                # A peer that announced a drain exits on purpose; its
                # FIN fails this collective immediately (no liveness
                # timeout) and the window belongs to the `preemption`
                # bucket, not `failure` (docs/goodput.md).
                peer_drained = drain.fleet_draining()
                logger.warning(
                    "collective failure%s; restoring last commit",
                    " (peer draining)" if peer_drained else "")
                goodput.disruption_begin(
                    "collective failure",
                    bucket="preemption" if peer_drained else "failure")
                _m_restores.inc()
                events_mod.emit(events_mod.ELASTIC_RESTORE,
                                severity=events_mod.WARN,
                                peer_drained=peer_drained)
                state.restore()
                # In-memory rollback to the last commit: steps past it
                # are replay badput.
                goodput.note_restore()
                skip_sync = False
            except HostsUpdatedInterrupt as e:
                logger.info("hosts updated; re-initializing")
                goodput.disruption_begin(
                    "hosts updated",
                    bucket="preemption" if drain.fleet_draining()
                    else "failure")
                _m_host_updates.inc()
                skip_sync = e.skip_sync
            _reset()
            state.on_reset()
            if ckpt_mgr is not None:
                # Counters are per-rank private state; a worker that
                # joined mid-run anchored at the restored step while
                # survivors kept counting. Re-anchor everyone on the
                # newest committed manifest so interval triggers stay
                # in lockstep across the new world.
                ckpt_mgr.resync_after_reset()
    finally:
        if ckpt_mgr is not None:
            state.set_checkpoint_manager(None)
            # Drain the writer: the last checkpoint of a clean exit is
            # the one a follow-up job restores.
            ckpt_mgr.stop()
            if checkpoint.current() is ckpt_mgr:
                checkpoint.set_current(None)
        notification_manager.remove_listener(state)
        # Back to unmanaged: a preemption notice during teardown (the
        # launcher's own stop path) exits cleanly from the handler.
        drain.coordinator.set_managed(False)
