"""Elastic run loop (ref: horovod/common/elastic.py:115-168 run_fn)."""
from __future__ import annotations

import functools
from typing import Callable

from ..common import basics, telemetry
from ..common.exceptions import HorovodInternalError, HostsUpdatedInterrupt
from ..utils.logging import get_logger
from .state import State

logger = get_logger()

# Elastic lifecycle counters (docs/metrics.md): a fleet whose
# resets_total climbs while restores_total stays flat is churning on
# topology changes; the reverse means workers keep dying mid-step.
_m_resets = telemetry.counter(
    "horovod_elastic_resets_total",
    "Full shutdown+init cycles taken by the elastic run loop")
_m_restores = telemetry.counter(
    "horovod_elastic_restores_total",
    "State restores after a collective failure (worker death)")
_m_host_updates = telemetry.counter(
    "horovod_elastic_host_updates_total",
    "Host add/remove notifications that interrupted training")


def _reset():
    """Full re-initialization with the new topology
    (ref: common/elastic.py reset → hvd.shutdown()+hvd.init();
    rank/size are re-read from the rendezvous-updated env)."""
    from ..backend import elastic_env

    _m_resets.inc()
    # shutdown() also stops the notification server (it must not leak
    # across resets); re-init it after the new topology lands so this
    # worker re-registers its endpoint — under the NEW epoch's env —
    # and keeps receiving host updates.
    basics.shutdown()
    elastic_env.refresh_topology_from_rendezvous()
    # init() re-sets the horovod_world_size gauge, so shrink/grow
    # history shows up next to the reset count.
    basics.init()
    elastic_env.notification_manager.init()


def run(func: Callable) -> Callable:
    """Decorator: `@hvd.elastic.run` (ref: common/elastic.py:115-130)."""

    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        return run_fn(func, state, *args, **kwargs)

    return wrapper


def run_fn(func: Callable, state: State, *args, **kwargs):
    """(ref: common/elastic.py:133-168)"""
    from ..backend.elastic_env import notification_manager

    notification_manager.init()
    notification_manager.register_listener(state)
    skip_sync = False
    try:
        while True:
            if not skip_sync:
                state.sync()
            try:
                return func(state, *args, **kwargs)
            except HorovodInternalError:
                logger.warning("collective failure; restoring last commit")
                _m_restores.inc()
                state.restore()
                skip_sync = False
            except HostsUpdatedInterrupt as e:
                logger.info("hosts updated; re-initializing")
                _m_host_updates.inc()
                skip_sync = e.skip_sync
            _reset()
            state.on_reset()
    finally:
        notification_manager.remove_listener(state)
