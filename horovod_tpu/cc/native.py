"""ctypes loader for the native core (libhvdtpu.so).

Mirrors the reference's extension-loading pattern (HorovodBasics ctypes
load, ref: horovod/common/basics.py:22-233 + check_extension,
horovod/common/util.py:50): build lazily with make on first use, cache
the handle, and fail soft — every caller has a NumPy fallback, so an
unbuildable environment degrades to pure Python instead of erroring.
Disable explicitly with HOROVOD_DISABLE_NATIVE=1.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libhvdtpu.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_DTYPES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
}
_OPS = {"sum": 0, "min": 1, "max": 2, "prod": 3}


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _DIR, "-s"],
            check=True, capture_output=True, timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except (subprocess.SubprocessError, OSError):
        return False


def load() -> Optional[ctypes.CDLL]:
    """The lib handle, building it if needed; None if unavailable."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("HOROVOD_DISABLE_NATIVE"):
            return None
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            lib.hvd_abi_version.restype = ctypes.c_int
            if lib.hvd_abi_version() != 1:
                return None
            lib.hvd_reduce.restype = ctypes.c_int
            lib.hvd_adasum.restype = ctypes.c_int
            _lib = lib
        except OSError:
            return None
    return _lib


def available() -> bool:
    return load() is not None


def native_built() -> bool:
    """Introspection à la mpi_built()/gloo_built()."""
    return available()


# ---------------------------------------------------------------------------
def reduce_arrays(op: str, arrays: Sequence[np.ndarray]) -> Optional[np.ndarray]:
    """k-way elementwise reduce; None → caller falls back to NumPy."""
    lib = load()
    if lib is None or not arrays:
        return None
    dt = _DTYPES.get(arrays[0].dtype)
    if dt is None or op not in _OPS:
        return None
    arrays = [np.ascontiguousarray(a) for a in arrays]
    out = np.empty_like(arrays[0])
    ptrs = (ctypes.c_void_p * len(arrays))(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays]
    )
    rc = lib.hvd_reduce(
        ptrs, len(arrays), arrays[0].size,
        out.ctypes.data_as(ctypes.c_void_p), dt, _OPS[op],
    )
    return out if rc == 0 else None


def pack(arrays: Sequence[np.ndarray]) -> Optional[np.ndarray]:
    """Concatenate raveled arrays into one byte buffer (fusion pack)."""
    lib = load()
    if lib is None:
        return None
    arrays = [np.ascontiguousarray(a) for a in arrays]
    sizes = (ctypes.c_int64 * len(arrays))(*[a.nbytes for a in arrays])
    total = sum(a.nbytes for a in arrays)
    dst = np.empty(total, np.uint8)
    ptrs = (ctypes.c_void_p * len(arrays))(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays]
    )
    lib.hvd_pack(ptrs, sizes, len(arrays),
                 dst.ctypes.data_as(ctypes.c_void_p))
    return dst


def unpack(buf: np.ndarray, shapes: List[tuple], dtype) -> Optional[List[np.ndarray]]:
    lib = load()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf.view(np.uint8).ravel())
    outs = [np.empty(s, dtype) for s in shapes]
    sizes = (ctypes.c_int64 * len(outs))(*[o.nbytes for o in outs])
    ptrs = (ctypes.c_void_p * len(outs))(
        *[o.ctypes.data_as(ctypes.c_void_p).value for o in outs]
    )
    lib.hvd_unpack(buf.ctypes.data_as(ctypes.c_void_p), sizes, len(outs), ptrs)
    return outs


def adasum(arrays: Sequence[np.ndarray]) -> Optional[List[np.ndarray]]:
    """In-place VHDD Adasum over a power-of-2 list; returns the combined
    result per input slot (all identical), original dtypes preserved."""
    lib = load()
    if lib is None:
        return None
    n = len(arrays)
    if n & (n - 1) != 0:
        return None
    f64 = [np.ascontiguousarray(a, np.float64).ravel() for a in arrays]
    ptrs = (ctypes.POINTER(ctypes.c_double) * n)(
        *[v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)) for v in f64]
    )
    rc = lib.hvd_adasum(ptrs, n, f64[0].size)
    if rc != 0:
        return None
    return [
        v.reshape(np.asarray(a).shape).astype(np.asarray(a).dtype)
        for v, a in zip(f64, arrays)
    ]
