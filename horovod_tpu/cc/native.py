"""ctypes loader for the native core (libhvdtpu.so).

Mirrors the reference's extension-loading pattern (HorovodBasics ctypes
load, ref: horovod/common/basics.py:22-233 + check_extension,
horovod/common/util.py:50): build lazily with make on first use, cache
the handle, and fail soft — every caller has a NumPy fallback, so an
unbuildable environment degrades to pure Python instead of erroring.

Every exported symbol gets ``argtypes``/``restype`` declared up front
(the ABI table below); a missing or re-typed symbol fails the load
loudly instead of corrupting buffers, and an ABI version mismatch
triggers one forced rebuild before giving up. ctypes releases the GIL
for the duration of each call, which is the whole point: segment k's
reduce overlaps segment k+1's recv on the engine's worker threads.

``HOROVOD_DISABLE_NATIVE=1`` is honoured per *call*, not per process:
the handle stays cached but every wrapper reports unavailable while
the variable is set, so tests and perf A/B stages can flip the ladder
live.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libhvdtpu.so")

ABI_VERSION = 2

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

# dtype codes shared with core.cc's HVD_DISPATCH_DTYPE. f16/bf16 are
# carried as their uint16 storage; the kernels compute in f32 with a
# round-to-storage per op (numpy's ufunc semantics for reduced floats).
_DTYPES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.uint8): 4,
    np.dtype(np.float16): 5,
}
try:
    import ml_dtypes as _ml_dtypes

    _DTYPES[np.dtype(_ml_dtypes.bfloat16)] = 6
except ImportError:  # pragma: no cover - jax images ship ml_dtypes
    _ml_dtypes = None

_OPS = {"sum": 0, "min": 1, "max": 2, "prod": 3}

# The full C ABI, declared for every export so drift fails at load
# time. name -> (restype, argtypes).
_P = ctypes.c_void_p
_I64 = ctypes.c_int64
_INT = ctypes.c_int
_SYMBOLS = {
    "hvd_abi_version": (_INT, []),
    "hvd_threads": (_INT, []),
    "hvd_reduce": (_INT, [_P, _INT, _I64, _P, _INT, _INT]),
    "hvd_reduce_into": (_INT, [_P, _P, _I64, _INT, _INT]),
    "hvd_reduce_strided": (_INT, [_P, _I64, _INT, _INT, _I64, _P, _INT,
                                  _INT, _INT]),
    "hvd_pack": (_INT, [_P, _P, _INT, _P]),
    "hvd_unpack": (_INT, [_P, _P, _INT, _P]),
    "hvd_bf16_encode": (_INT, [_P, _I64, _P]),
    "hvd_bf16_decode": (_INT, [_P, _I64, _P]),
    "hvd_fp16_encode": (_INT, [_P, _I64, _P]),
    "hvd_fp16_decode": (_INT, [_P, _I64, _P]),
    "hvd_int8_encode": (_INT, [_P, _I64, _P]),
    "hvd_int8_decode": (_INT, [_P, _I64, _P]),
    "hvd_ef_update": (_INT, [_P, _P, _P, _I64]),
    "hvd_adasum": (_INT, [_P, _INT, _I64]),
    "hvd_words_op": (None, [_P, _P, _INT, _INT]),
}

# Kernel inventory for /status: wrapper-level feature -> C symbols it
# needs. Everything ships in one .so, but reporting per kernel keeps
# the operator story honest if the table ever splits.
_KERNELS = {
    "reduce": ["hvd_reduce"],
    "reduce_into": ["hvd_reduce_into"],
    "reduce_strided": ["hvd_reduce_strided"],
    "pack": ["hvd_pack", "hvd_unpack"],
    "bf16": ["hvd_bf16_encode", "hvd_bf16_decode"],
    "fp16": ["hvd_fp16_encode", "hvd_fp16_decode"],
    "int8": ["hvd_int8_encode", "hvd_int8_decode"],
    "ef_update": ["hvd_ef_update"],
    "adasum": ["hvd_adasum"],
    "words_op": ["hvd_words_op"],
}


def _disabled() -> bool:
    return bool(os.environ.get("HOROVOD_DISABLE_NATIVE"))


def _build(force: bool = False) -> bool:
    try:
        if force and os.path.exists(_LIB_PATH):
            os.remove(_LIB_PATH)
        subprocess.run(
            ["make", "-C", _DIR, "-s"],
            check=True, capture_output=True, timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except (subprocess.SubprocessError, OSError):
        return False


def _declare(lib: ctypes.CDLL) -> bool:
    """Declare the whole ABI table; False if any symbol is missing."""
    try:
        for name, (restype, argtypes) in _SYMBOLS.items():
            fn = getattr(lib, name)
            fn.restype = restype
            fn.argtypes = argtypes
        return True
    except AttributeError:
        return False


def _open() -> Optional[ctypes.CDLL]:
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    try:
        lib.hvd_abi_version.restype = ctypes.c_int
        if lib.hvd_abi_version() != ABI_VERSION:
            return None
        return lib if _declare(lib) else None
    except AttributeError:
        return None


def load() -> Optional[ctypes.CDLL]:
    """The lib handle, building it if needed; None if unavailable or
    HOROVOD_DISABLE_NATIVE is set right now."""
    global _lib, _tried
    if _disabled():
        return None
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        lib = _open()
        if lib is None:
            # Stale .so (e.g. a checkout from an older ABI): one
            # forced rebuild before degrading to the numpy ladder.
            if _build(force=True):
                lib = _open()
        _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def native_built() -> bool:
    """Introspection à la mpi_built()/gloo_built()."""
    return available()


def abi_version() -> Optional[int]:
    lib = load()
    return ABI_VERSION if lib is not None else None


def kernel_inventory() -> Dict[str, bool]:
    """Kernel name -> active (native) vs False (numpy fallback)."""
    lib = load()
    up = lib is not None
    return {k: up for k in _KERNELS}


def status() -> dict:
    """Native-core status for /status and the hvdtop badge."""
    loaded = available()
    return {
        "built": os.path.exists(_LIB_PATH),
        "loaded": loaded,
        "disabled": _disabled(),
        "abi": ABI_VERSION if loaded else None,
        "threads": threads() if loaded else None,
        "kernels": kernel_inventory(),
    }


def threads() -> Optional[int]:
    lib = load()
    return int(lib.hvd_threads()) if lib is not None else None


def _ptr(a: np.ndarray) -> int:
    return a.ctypes.data


# Below this size the in-place reduce stays on numpy: its in-cache
# ufunc kernels beat the ctypes round-trip + native loop on a
# single-core host (measured crossover ~8MB; docs/native.md), and
# with no pool workers the GIL-free property buys no overlap either.
# With workers the kernel parallelizes and the call is GIL-free, so
# every size is worth taking. HOROVOD_NATIVE_REDUCE_MIN_BYTES
# overrides (0 = always native).
_REDUCE_INTO_MIN_BYTES = 8 << 20
_pool_floor: Optional[int] = None


def _reduce_into_floor() -> int:
    env = os.environ.get("HOROVOD_NATIVE_REDUCE_MIN_BYTES")
    if env is not None:
        try:
            return max(int(env), 0)
        except ValueError:
            pass
    global _pool_floor
    if _pool_floor is None:
        _pool_floor = 0 if (threads() or 1) > 1 else _REDUCE_INTO_MIN_BYTES
    return _pool_floor


# ---------------------------------------------------------------------------
def reduce_arrays(op: str, arrays: Sequence[np.ndarray]) -> Optional[np.ndarray]:
    """k-way elementwise reduce; None → caller falls back to NumPy."""
    lib = load()
    if lib is None or not arrays:
        return None
    dt = _DTYPES.get(arrays[0].dtype)
    if dt is None or op not in _OPS:
        return None
    arrays = [np.ascontiguousarray(a) for a in arrays]
    out = np.empty_like(arrays[0])
    ptrs = (ctypes.c_void_p * len(arrays))(*[_ptr(a) for a in arrays])
    rc = lib.hvd_reduce(ptrs, len(arrays), arrays[0].size, _ptr(out), dt,
                        _OPS[op])
    return out if rc == 0 else None


def reduce_into(op: str, tgt: np.ndarray, src: np.ndarray,
                hint_bytes: int = 0) -> bool:
    """In-place ``tgt op= src`` (the ring's recv+reduce step), GIL-free.
    False → caller runs the ufunc fallback.

    ``hint_bytes`` is the caller's working-set size when ``tgt`` is one
    segment of a larger message (the segmented ring): the cache-hot
    crossover is governed by the whole message, not the segment, and a
    DRAM-bound pipeline also wants the GIL released so segment k's
    reduce overlaps segment k+1's recv."""
    lib = load()
    if lib is None or op not in _OPS:
        return False
    dt = _DTYPES.get(tgt.dtype)
    if (dt is None or tgt.dtype != src.dtype or tgt.size != src.size
            or not tgt.flags.c_contiguous or not src.flags.c_contiguous
            or not tgt.flags.writeable
            or max(tgt.nbytes, hint_bytes) < _reduce_into_floor()):
        return False
    rc = lib.hvd_reduce_into(_ptr(tgt), _ptr(src), tgt.size, dt, _OPS[op])
    return rc == 0


def reduce_strided(op: str, buf: np.ndarray, offset: int, stride: int,
                   nsrc: int, skip: int, out: np.ndarray,
                   init: bool) -> bool:
    """Fused gather-reduce over ``nsrc`` peer slices living at byte
    ``offset + r*stride`` inside the arena byte buffer ``buf``; reduces
    straight into ``out`` (seeding it when ``init``, else accumulating),
    skipping peer ``skip`` (< 0: none). False → caller loops in numpy."""
    lib = load()
    if lib is None or op not in _OPS or nsrc <= 0:
        return False
    dt = _DTYPES.get(out.dtype)
    if (dt is None or not out.flags.c_contiguous
            or not out.flags.writeable or offset < 0 or stride < 0):
        return False
    n = out.size
    if offset + (nsrc - 1) * stride + n * out.itemsize > buf.nbytes:
        return False
    rc = lib.hvd_reduce_strided(_ptr(buf) + int(offset), int(stride),
                                int(nsrc), int(skip), n, _ptr(out), dt,
                                _OPS[op], 1 if init else 0)
    return rc == 0


def pack(arrays: Sequence[np.ndarray]) -> Optional[np.ndarray]:
    """Concatenate raveled arrays into one byte buffer (fusion pack)."""
    lib = load()
    if lib is None:
        return None
    arrays = [np.ascontiguousarray(a) for a in arrays]
    sizes = (ctypes.c_int64 * len(arrays))(*[a.nbytes for a in arrays])
    total = sum(a.nbytes for a in arrays)
    dst = np.empty(total, np.uint8)
    ptrs = (ctypes.c_void_p * len(arrays))(*[_ptr(a) for a in arrays])
    rc = lib.hvd_pack(ptrs, sizes, len(arrays), _ptr(dst))
    return dst if rc == 0 else None


def unpack(buf: np.ndarray, shapes: List[tuple], dtype) -> Optional[List[np.ndarray]]:
    lib = load()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf.view(np.uint8).ravel())
    outs = [np.empty(s, dtype) for s in shapes]
    sizes = (ctypes.c_int64 * len(outs))(*[o.nbytes for o in outs])
    ptrs = (ctypes.c_void_p * len(outs))(*[_ptr(o) for o in outs])
    rc = lib.hvd_unpack(_ptr(buf), sizes, len(outs), ptrs)
    return outs if rc == 0 else None


# ---------------------------------------------------------------------------
# wire codec passes (bit-identical to common/compression.py fallbacks)

def _as_f32_1d(a: np.ndarray) -> Optional[np.ndarray]:
    if a.dtype != np.float32 or not a.flags.c_contiguous or a.ndim != 1:
        return None
    return a


def bf16_encode(a: np.ndarray) -> Optional[np.ndarray]:
    lib = load()
    a = _as_f32_1d(a) if lib is not None else None
    if a is None:
        return None
    out = np.empty(a.size, np.uint16)
    rc = lib.hvd_bf16_encode(_ptr(a), a.size, _ptr(out))
    return out.view(np.uint8) if rc == 0 else None


def bf16_decode(buf, count: int) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    src = np.frombuffer(buf, dtype=np.uint8, count=2 * count)
    out = np.empty(count, np.float32)
    rc = lib.hvd_bf16_decode(_ptr(src), count, _ptr(out))
    return out if rc == 0 else None


def fp16_encode(a: np.ndarray) -> Optional[np.ndarray]:
    lib = load()
    a = _as_f32_1d(a) if lib is not None else None
    if a is None:
        return None
    out = np.empty(a.size, np.uint16)
    rc = lib.hvd_fp16_encode(_ptr(a), a.size, _ptr(out))
    return out.view(np.uint8) if rc == 0 else None


def fp16_decode(buf, count: int) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    src = np.frombuffer(buf, dtype=np.uint8, count=2 * count)
    out = np.empty(count, np.float32)
    rc = lib.hvd_fp16_decode(_ptr(src), count, _ptr(out))
    return out if rc == 0 else None


def int8_encode(a: np.ndarray) -> Optional[np.ndarray]:
    """Scale header (4B LE f32) + quantized bytes, like Int8Codec."""
    lib = load()
    a = _as_f32_1d(a) if lib is not None else None
    if a is None:
        return None
    out = np.empty(4 + a.size, np.uint8)
    rc = lib.hvd_int8_encode(_ptr(a), a.size, _ptr(out))
    return out if rc == 0 else None


def int8_decode(buf, count: int) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    src = np.frombuffer(buf, dtype=np.uint8, count=4 + count)
    out = np.empty(count, np.float32)
    rc = lib.hvd_int8_decode(_ptr(src), count, _ptr(out))
    return out if rc == 0 else None


def ef_update(residual: np.ndarray, pre: np.ndarray,
              wire: np.ndarray) -> bool:
    """residual = pre - wire with non-finite lanes zeroed, in place."""
    lib = load()
    if lib is None:
        return False
    if not (residual.dtype == pre.dtype == wire.dtype == np.float32):
        return False
    if not (residual.size == pre.size == wire.size):
        return False
    for a in (residual, pre, wire):
        if not a.flags.c_contiguous:
            return False
    if not residual.flags.writeable:
        return False
    rc = lib.hvd_ef_update(_ptr(residual), _ptr(pre), _ptr(wire),
                           residual.size)
    return rc == 0


# ---------------------------------------------------------------------------
def adasum(arrays: Sequence[np.ndarray]) -> Optional[List[np.ndarray]]:
    """In-place VHDD Adasum over a power-of-2 list; returns the combined
    result per input slot (all identical), original dtypes preserved."""
    lib = load()
    if lib is None:
        return None
    n = len(arrays)
    if n & (n - 1) != 0:
        return None
    f64 = [np.ascontiguousarray(a, np.float64).ravel() for a in arrays]
    ptrs = (ctypes.POINTER(ctypes.c_double) * n)(
        *[v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)) for v in f64]
    )
    rc = lib.hvd_adasum(ptrs, n, f64[0].size)
    if rc != 0:
        return None
    return [
        v.reshape(np.asarray(a).shape).astype(np.asarray(a).dtype)
        for v, a in zip(f64, arrays)
    ]
