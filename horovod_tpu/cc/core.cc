// Native core for the eager engine's host-side hot paths.
//
// The reference implements its whole runtime in C++ (horovod/common/ —
// operations.cc, collective_operations.cc fusion memcpys, adasum/adasum.h
// VHDD math). On TPU the *device* hot path is XLA; what remains hot on
// the host in process mode is exactly what lives here:
//
//   * k-way reduction kernels for the star data plane
//     (ref: CPU ScaleBuffer/allreduce paths, collective_operations.h:89-125)
//   * fusion-buffer pack/unpack, multithreaded memcpy
//     (ref: MemcpyInFusionBuffer/MemcpyOutFusionBuffer)
//   * the Adasum pairwise recursion with float64 dot/norm accumulation
//     (ref: ops/adasum/adasum.h:100-280)
//   * bit-vector AND/OR for cache coordination
//     (ref: response_cache.h bitvector sync)
//
// Exposed as a plain C ABI consumed via ctypes (horovod_tpu/cc/native.py)
// — the same load pattern as the reference's HorovodBasics
// (horovod/common/basics.py:22-233), no pybind dependency.
//
// Build: `make -C horovod_tpu/cc` (g++ -O3 -shared; no external deps).

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int64_t kParallelThresholdBytes = 1 << 20;  // 1 MB

int hardware_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n == 0 ? 2 : static_cast<int>(n);
}

// Run fn(begin, end) over [0, n) in roughly equal chunks.
template <typename F>
void parallel_for(int64_t n, int64_t grain, F fn) {
  int nthreads = hardware_threads();
  if (n < grain || nthreads <= 1) {
    fn(0, n);
    return;
  }
  int chunks = std::min<int64_t>(nthreads, (n + grain - 1) / grain);
  std::vector<std::thread> threads;
  threads.reserve(chunks - 1);
  int64_t per = (n + chunks - 1) / chunks;
  for (int c = 1; c < chunks; ++c) {
    int64_t b = c * per, e = std::min<int64_t>(n, b + per);
    if (b >= e) break;
    threads.emplace_back([=] { fn(b, e); });
  }
  fn(0, std::min<int64_t>(n, per));
  for (auto& t : threads) t.join();
}

template <typename T>
void reduce_impl(const T** srcs, int nsrc, int64_t len, T* out, int op) {
  // op: 0=sum, 1=min, 2=max, 3=prod
  parallel_for(len, 1 << 16, [&](int64_t b, int64_t e) {
    std::memcpy(out + b, srcs[0] + b, (e - b) * sizeof(T));
    for (int s = 1; s < nsrc; ++s) {
      const T* src = srcs[s];
      switch (op) {
        case 0:
          for (int64_t i = b; i < e; ++i) out[i] += src[i];
          break;
        case 1:
          for (int64_t i = b; i < e; ++i)
            out[i] = src[i] < out[i] ? src[i] : out[i];
          break;
        case 2:
          for (int64_t i = b; i < e; ++i)
            out[i] = src[i] > out[i] ? src[i] : out[i];
          break;
        case 3:
          for (int64_t i = b; i < e; ++i) out[i] *= src[i];
          break;
      }
    }
  });
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// k-way elementwise reduction. dtype: 0=f32, 1=f64, 2=i32, 3=i64.
// Returns 0 on success, -1 on bad dtype/op.
int hvd_reduce(const void** srcs, int nsrc, int64_t len, void* out, int dtype,
               int op) {
  if (nsrc <= 0 || op < 0 || op > 3) return -1;
  switch (dtype) {
    case 0:
      reduce_impl(reinterpret_cast<const float**>(srcs), nsrc, len,
                  static_cast<float*>(out), op);
      return 0;
    case 1:
      reduce_impl(reinterpret_cast<const double**>(srcs), nsrc, len,
                  static_cast<double*>(out), op);
      return 0;
    case 2:
      reduce_impl(reinterpret_cast<const int32_t**>(srcs), nsrc, len,
                  static_cast<int32_t*>(out), op);
      return 0;
    case 3:
      reduce_impl(reinterpret_cast<const int64_t**>(srcs), nsrc, len,
                  static_cast<int64_t*>(out), op);
      return 0;
    default:
      return -1;
  }
}

// ---------------------------------------------------------------------------
// Fusion buffer pack/unpack (ref: MemcpyIn/OutFusionBuffer).
void hvd_pack(const void** srcs, const int64_t* nbytes, int n, void* dst) {
  std::vector<int64_t> offs(n + 1, 0);
  for (int i = 0; i < n; ++i) offs[i + 1] = offs[i] + nbytes[i];
  if (offs[n] >= kParallelThresholdBytes && n > 1) {
    std::atomic<int> next{0};
    int nthreads = std::min(hardware_threads(), n);
    std::vector<std::thread> threads;
    for (int t = 0; t < nthreads; ++t)
      threads.emplace_back([&] {
        int i;
        while ((i = next.fetch_add(1)) < n)
          std::memcpy(static_cast<char*>(dst) + offs[i], srcs[i], nbytes[i]);
      });
    for (auto& th : threads) th.join();
  } else {
    for (int i = 0; i < n; ++i)
      std::memcpy(static_cast<char*>(dst) + offs[i], srcs[i], nbytes[i]);
  }
}

void hvd_unpack(const void* src, const int64_t* nbytes, int n, void** dsts) {
  int64_t off = 0;
  for (int i = 0; i < n; ++i) {
    std::memcpy(dsts[i], static_cast<const char*>(src) + off, nbytes[i]);
    off += nbytes[i];
  }
}

// ---------------------------------------------------------------------------
// Adasum (ref: adasum.h:100-280). vecs: nvec pointers to f64 arrays of
// length n, combined IN PLACE so that every slot holds the Adasum result.
// nvec must be a power of two. Dot/norm accumulation is f64 end-to-end
// like the reference's DispatchComputeDotAndNormSqrds.
int hvd_adasum(double** vecs, int nvec, int64_t n) {
  if (nvec <= 0 || (nvec & (nvec - 1)) != 0) return -1;
  std::vector<std::vector<double>> scratch(nvec);
  for (int stride = 1; stride < nvec; stride <<= 1) {
    // Each unordered pair (i, i^stride) combines symmetrically.
    for (int i = 0; i < nvec; ++i) {
      int j = i ^ stride;
      if (j < i) continue;
      const double* a = vecs[i];
      const double* b = vecs[j];
      double dot = 0.0, na = 0.0, nb = 0.0;
      // Threaded partial sums for big vectors.
      if (n >= (1 << 18)) {
        int nthreads = hardware_threads();
        std::vector<double> pd(nthreads, 0), pa(nthreads, 0), pb(nthreads, 0);
        std::vector<std::thread> threads;
        int64_t per = (n + nthreads - 1) / nthreads;
        for (int t = 0; t < nthreads; ++t)
          threads.emplace_back([&, t] {
            int64_t b0 = t * per, e0 = std::min(n, b0 + per);
            double d = 0, x = 0, y = 0;
            for (int64_t k = b0; k < e0; ++k) {
              d += a[k] * b[k];
              x += a[k] * a[k];
              y += b[k] * b[k];
            }
            pd[t] = d;
            pa[t] = x;
            pb[t] = y;
          });
        for (auto& th : threads) th.join();
        for (int t = 0; t < nthreads; ++t) {
          dot += pd[t];
          na += pa[t];
          nb += pb[t];
        }
      } else {
        for (int64_t k = 0; k < n; ++k) {
          dot += a[k] * b[k];
          na += a[k] * a[k];
          nb += b[k] * b[k];
        }
      }
      double ca = na > 0 ? 1.0 - dot / (2.0 * na) : 1.0;
      double cb = nb > 0 ? 1.0 - dot / (2.0 * nb) : 1.0;
      auto& tmp = scratch[i];
      tmp.resize(n);
      parallel_for(n, 1 << 16, [&](int64_t b0, int64_t e0) {
        for (int64_t k = b0; k < e0; ++k) tmp[k] = ca * a[k] + cb * b[k];
      });
      std::memcpy(vecs[i], tmp.data(), n * sizeof(double));
      std::memcpy(vecs[j], tmp.data(), n * sizeof(double));
    }
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Bit-vector ops (ref: response_cache.h). op: 0=and, 1=or.
void hvd_words_op(uint64_t* acc, const uint64_t* other, int n, int op) {
  if (op == 0)
    for (int i = 0; i < n; ++i) acc[i] &= other[i];
  else
    for (int i = 0; i < n; ++i) acc[i] |= other[i];
}

int hvd_abi_version() { return 1; }

}  // extern "C"
