// Native core for the eager engine's host-side hot paths.
//
// The reference implements its whole runtime in C++ (horovod/common/ —
// operations.cc, collective_operations.cc fusion memcpys, adasum/adasum.h
// VHDD math). On TPU the *device* hot path is XLA; what remains hot on
// the host in process mode is exactly what lives here:
//
//   * per-segment in-place reduce for the ring's recv+reduce step
//     (ref: CPU allreduce inner loops, collective_operations.h:89-125)
//   * fused strided gather-reduce over the shm arena's deposit slots —
//     one pass over all peers instead of per-peer numpy adds
//   * k-way reduction kernels for the star data plane
//   * wire-codec passes: bf16/fp16/int8-with-scale encode/decode and
//     the error-feedback residual update, bit-compatible with the
//     numpy fallbacks in common/compression.py (rank-consistency
//     requires every host to produce the same wire bytes regardless
//     of whether it runs native or fallback)
//   * fusion-buffer pack/unpack, multithreaded memcpy
//     (ref: MemcpyInFusionBuffer/MemcpyOutFusionBuffer)
//   * the Adasum pairwise recursion with float64 dot/norm accumulation
//     (ref: ops/adasum/adasum.h:100-280)
//   * bit-vector AND/OR for cache coordination
//     (ref: response_cache.h bitvector sync)
//
// Exposed as a plain C ABI consumed via ctypes (horovod_tpu/cc/native.py)
// — the same load pattern as the reference's HorovodBasics
// (horovod/common/basics.py:22-233), no pybind dependency. ctypes
// releases the GIL for the duration of every call, so segment k's
// reduce genuinely overlaps segment k+1's recv across engine threads.
//
// Threading: one persistent worker pool (lazy, HOROVOD_NATIVE_THREADS,
// re-created after fork) instead of per-call std::thread spawns; on a
// single-core host the pool has zero workers and every kernel runs
// inline on the calling thread — still GIL-free.
//
// Build: `make -C horovod_tpu/cc` (g++ -O3 -shared; no external deps).

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

#include <stdlib.h>
#include <unistd.h>

namespace {

constexpr int64_t kParallelThresholdBytes = 1 << 20;  // 1 MB
constexpr int64_t kGrainElems = 1 << 16;

int hardware_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n == 0 ? 2 : static_cast<int>(n);
}

int configured_threads() {
  const char* env = getenv("HOROVOD_NATIVE_THREADS");
  if (env != nullptr && env[0] != '\0') {
    int v = atoi(env);
    if (v >= 1) return v > 64 ? 64 : v;
  }
  int hw = hardware_threads();
  return hw > 8 ? 8 : hw;  // memory-bound kernels saturate early
}

// Persistent worker pool. Callers hand it a chunk-indexed job; workers
// and the caller grab chunks from a shared atomic counter. try_run is
// non-blocking for concurrent callers: if another thread owns the pool
// (or the pool has no workers), the caller runs its job inline —
// graceful degradation instead of cross-channel serialization.
class Pool {
 public:
  explicit Pool(int workers) {
    for (int i = 0; i < workers; ++i)
      threads_.emplace_back([this] { worker_loop(); });
  }
  int workers() const { return static_cast<int>(threads_.size()); }

  bool try_run(int nchunks, const std::function<void(int)>& fn) {
    if (threads_.empty() || nchunks <= 0) return false;
    if (!run_mu_.try_lock()) return false;
    {
      std::unique_lock<std::mutex> lk(mu_);
      // Wait out stragglers from the previous epoch before resetting
      // the shared job state they may still be reading.
      idle_cv_.wait(lk, [this] { return active_ == 0; });
      job_ = &fn;
      nchunks_ = nchunks;
      next_.store(0, std::memory_order_relaxed);
      pending_.store(nchunks, std::memory_order_relaxed);
      ++epoch_;
      cv_.notify_all();
    }
    work();
    {
      std::unique_lock<std::mutex> lk(mu_);
      done_cv_.wait(lk, [this] { return pending_.load() == 0; });
      job_ = nullptr;
    }
    run_mu_.unlock();
    return true;
  }

 private:
  void work() {
    int i;
    while ((i = next_.fetch_add(1)) < nchunks_) {
      (*job_)(i);
      if (pending_.fetch_sub(1) == 1) {
        std::lock_guard<std::mutex> lk(mu_);
        done_cv_.notify_all();
      }
    }
  }
  void worker_loop() {
    uint64_t seen = 0;
    for (;;) {
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return epoch_ != seen; });
        seen = epoch_;
        ++active_;
      }
      work();
      {
        std::lock_guard<std::mutex> lk(mu_);
        --active_;
        if (active_ == 0) idle_cv_.notify_all();
      }
    }
  }

  std::mutex run_mu_;  // one job at a time; losers run inline
  std::mutex mu_;
  std::condition_variable cv_, done_cv_, idle_cv_;
  const std::function<void(int)>* job_ = nullptr;
  std::atomic<int> next_{0};
  std::atomic<int> pending_{0};
  int nchunks_ = 0;
  int active_ = 0;
  uint64_t epoch_ = 0;
  std::vector<std::thread> threads_;
};

// Lock-free singleton keyed by pid: a fork (Python multiprocessing)
// leaves the parent's workers behind, so the child lazily builds a
// fresh pool. The stale pool leaks — its mutexes may have been copied
// mid-acquire, so it is never touched again.
std::atomic<Pool*> g_pool{nullptr};
std::atomic<long> g_pool_pid{0};

Pool* pool() {
  long pid = static_cast<long>(getpid());
  Pool* p = g_pool.load(std::memory_order_acquire);
  if (p != nullptr && g_pool_pid.load(std::memory_order_acquire) == pid)
    return p;
  Pool* fresh = new Pool(configured_threads() - 1);
  Pool* expected = p;
  if (g_pool.compare_exchange_strong(expected, fresh,
                                     std::memory_order_acq_rel)) {
    g_pool_pid.store(pid, std::memory_order_release);
    return fresh;
  }
  delete fresh;  // lost the race before any worker had work
  return g_pool.load(std::memory_order_acquire);
}

// Run fn(begin, end) over [0, n) in roughly equal chunks.
template <typename F>
void parallel_for(int64_t n, int64_t grain, const F& fn) {
  if (n <= 0) return;
  Pool* p = pool();
  int nthreads = (p != nullptr ? p->workers() : 0) + 1;
  int64_t chunks = (n + grain - 1) / grain;
  if (chunks > nthreads) chunks = nthreads;
  if (chunks <= 1 || p == nullptr || p->workers() == 0) {
    fn(0, n);
    return;
  }
  int64_t per = (n + chunks - 1) / chunks;
  std::function<void(int)> job = [&](int c) {
    int64_t b = c * per, e = std::min<int64_t>(n, b + per);
    if (b < e) fn(b, e);
  };
  if (!p->try_run(static_cast<int>(chunks), job)) fn(0, n);
}

// ---------------------------------------------------------------------------
// IEEE conversions, bit-exact vs the numpy fallbacks. The data plane's
// rank-consistency contract needs native and fallback hosts to emit
// identical wire bytes, so these mirror numpy's halffloat.c and the
// compression.py bf16 bit path operation for operation.

inline float bits_to_float(uint32_t u) {
  float f;
  std::memcpy(&f, &u, 4);
  return f;
}

inline uint32_t float_to_bits(float f) {
  uint32_t u;
  std::memcpy(&u, &f, 4);
  return u;
}

inline float bf16_to_float(uint16_t b) {
  return bits_to_float(static_cast<uint32_t>(b) << 16);
}

inline uint16_t float_to_bf16(float f) {
  uint32_t u = float_to_bits(f);
  // NaN: canonical quiet NaN, exactly like the ml_dtypes cast the
  // numpy fallback uses (payload dropped). inf needs no special case:
  // its mantissa is zero so the RNE add cannot carry into the
  // exponent and truncation falls out of the shift. One select keeps
  // the loop branchless, which is what lets the SIMD clones vectorize
  // it (ml_dtypes' Eigen cast is vectorized; matching its speed
  // requires matching its shape).
  uint32_t lsb = (u >> 16) & 1u;
  uint16_t r = static_cast<uint16_t>((u + 0x7FFFu + lsb) >> 16);
  uint16_t canon = (u & 0x80000000u) != 0 ? 0xFFC0u : 0x7FC0u;
  return (u & 0x7FFFFFFFu) > 0x7F800000u ? canon : r;
}

inline float half_to_float(uint16_t h) {
  uint32_t sign = static_cast<uint32_t>(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1Fu;
  uint32_t man = h & 0x3FFu;
  if (exp == 0) {
    if (man == 0) return bits_to_float(sign);
    int shift = 0;
    while ((man & 0x400u) == 0) {
      man <<= 1;
      ++shift;
    }
    man &= 0x3FFu;
    return bits_to_float(
        sign | (static_cast<uint32_t>(113 - shift) << 23) | (man << 13));
  }
  if (exp == 31) return bits_to_float(sign | 0x7F800000u | (man << 13));
  return bits_to_float(sign | ((exp + 112u) << 23) | (man << 13));
}

// Runtime SIMD dispatch (docs/native.md): the .so must run on any
// x86-64 host, so instead of -march=native the hot loops are compiled
// once per ISA (baseline SSE2 / AVX2 / AVX-512) and glibc's ifunc
// resolver picks the widest the CPU supports at load time. Every
// clone performs the same IEEE operations in the same order — wider
// registers only — so results stay bitwise identical across hosts,
// which the rank-consistency contract requires.
#if defined(__x86_64__) && defined(__GNUC__) && !defined(__clang__)
#define HVD_SIMD_CLONES \
  __attribute__((target_clones("default", "avx2", "avx512f")))
#else
#define HVD_SIMD_CLONES
#endif

inline uint16_t float_to_half(float f) {
  uint32_t u = float_to_bits(f);
  uint16_t sign = static_cast<uint16_t>((u >> 16) & 0x8000u);
  uint32_t x = u & 0x7FFFFFFFu;
  if (x >= 0x7F800000u) {  // inf / NaN
    if (x == 0x7F800000u) return sign | 0x7C00u;
    uint16_t sig = static_cast<uint16_t>((x & 0x007FFFFFu) >> 13);
    if (sig == 0) sig = 1;  // keep NaN a NaN after truncation
    return static_cast<uint16_t>(sign | 0x7C00u | sig);
  }
  if (x >= 0x477FF000u) return sign | 0x7C00u;  // rounds past max finite
  if (x >= 0x38800000u) {                       // normal half
    uint32_t lsb = (x >> 13) & 1u;
    x += 0xFFFu + lsb;
    return static_cast<uint16_t>(sign | ((x - 0x38000000u) >> 13));
  }
  if (x <= 0x33000000u) return sign;  // underflow (tie at 2^-25 -> even)
  // subnormal half: round man * 2^(e-150) to multiples of 2^-24
  uint32_t e = x >> 23;
  uint32_t man = (x & 0x007FFFFFu) | 0x00800000u;
  int shift = 126 - static_cast<int>(e);  // 14..24 in this range
  uint32_t shifted = man >> shift;
  uint32_t rem = man & ((1u << shift) - 1u);
  uint32_t half = 1u << (shift - 1);
  if (rem > half || (rem == half && (shifted & 1u))) ++shifted;
  return static_cast<uint16_t>(sign | shifted);
}

// ---------------------------------------------------------------------------
// dtype traits: S = storage element, C = compute type. Reduced floats
// compute in f32 with a round-to-storage per op — exactly numpy's
// float16/bfloat16 ufunc semantics, so native and fallback agree
// bitwise.

template <typename T>
struct Plain {
  using S = T;
  using C = T;
  static inline C ld(S v) { return v; }
  static inline S st(C v) { return v; }
};

struct Half {
  using S = uint16_t;
  using C = float;
  static inline C ld(S v) { return half_to_float(v); }
  static inline S st(C v) { return float_to_half(v); }
};

struct Bf16 {
  using S = uint16_t;
  using C = float;
  static inline C ld(S v) { return bf16_to_float(v); }
  static inline S st(C v) { return float_to_bf16(v); }
};

// op: 0=sum, 1=min, 2=max, 3=prod. min/max comparison semantics match
// the pre-existing f32 kernel (first operand wins on NaN), used on
// finite data by every caller.
template <typename TR>
inline void reduce_into_range(typename TR::S* tgt, const typename TR::S* src,
                              int64_t b, int64_t e, int op) {
  switch (op) {
    case 0:
      for (int64_t i = b; i < e; ++i)
        tgt[i] = TR::st(TR::ld(tgt[i]) + TR::ld(src[i]));
      break;
    case 1:
      for (int64_t i = b; i < e; ++i) {
        auto s = TR::ld(src[i]);
        auto t = TR::ld(tgt[i]);
        tgt[i] = TR::st(s < t ? s : t);
      }
      break;
    case 2:
      for (int64_t i = b; i < e; ++i) {
        auto s = TR::ld(src[i]);
        auto t = TR::ld(tgt[i]);
        tgt[i] = TR::st(s > t ? s : t);
      }
      break;
    case 3:
      for (int64_t i = b; i < e; ++i)
        tgt[i] = TR::st(TR::ld(tgt[i]) * TR::ld(src[i]));
      break;
  }
}

// SIMD-cloned entry for the hot gradient dtypes; everything else
// takes the generic template (u8/f16/bf16 go through per-element
// converters the vectorizer handles inside the clone anyway, but only
// f32/f64 carry enough traffic to justify a clone set each).
HVD_SIMD_CLONES void reduce_range_f32(float* t, const float* s, int64_t b,
                                      int64_t e, int op) {
  reduce_into_range<Plain<float>>(t, s, b, e, op);
}

HVD_SIMD_CLONES void reduce_range_f64(double* t, const double* s, int64_t b,
                                      int64_t e, int op) {
  reduce_into_range<Plain<double>>(t, s, b, e, op);
}

HVD_SIMD_CLONES void reduce_range_bf16(uint16_t* t, const uint16_t* s,
                                       int64_t b, int64_t e, int op) {
  reduce_into_range<Bf16>(t, s, b, e, op);
}

template <typename TR>
inline void reduce_range(typename TR::S* t, const typename TR::S* s,
                         int64_t b, int64_t e, int op) {
  reduce_into_range<TR>(t, s, b, e, op);
}

template <>
inline void reduce_range<Plain<float>>(float* t, const float* s, int64_t b,
                                       int64_t e, int op) {
  reduce_range_f32(t, s, b, e, op);
}

template <>
inline void reduce_range<Plain<double>>(double* t, const double* s,
                                        int64_t b, int64_t e, int op) {
  reduce_range_f64(t, s, b, e, op);
}

template <>
inline void reduce_range<Bf16>(uint16_t* t, const uint16_t* s, int64_t b,
                               int64_t e, int op) {
  reduce_range_bf16(t, s, b, e, op);
}

template <typename TR>
void reduce_into_t(void* tgt, const void* src, int64_t len, int op) {
  auto* t = static_cast<typename TR::S*>(tgt);
  auto* s = static_cast<const typename TR::S*>(src);
  parallel_for(len, kGrainElems, [&](int64_t b, int64_t e) {
    reduce_range<TR>(t, s, b, e, op);
  });
}

template <typename TR>
void reduce_kway_t(const void** srcs, int nsrc, int64_t len, void* out,
                   int op) {
  auto* o = static_cast<typename TR::S*>(out);
  parallel_for(len, kGrainElems, [&](int64_t b, int64_t e) {
    std::memcpy(o + b, static_cast<const typename TR::S*>(srcs[0]) + b,
                (e - b) * sizeof(typename TR::S));
    for (int s = 1; s < nsrc; ++s)
      reduce_range<TR>(o, static_cast<const typename TR::S*>(srcs[s]), b, e,
                       op);
  });
}

// Fused arena gather-reduce: nsrc peer deposits at a fixed byte stride
// from base, reduced in one pass per chunk (read k, write 1 — the
// per-peer numpy loop reads AND writes the accumulator every peer).
// skip < 0 means none; init != 0 seeds out from the first non-skipped
// source, else out accumulates in place. Rank order is preserved so
// results stay bitwise identical to the Python loop.
template <typename TR>
void reduce_strided_t(const uint8_t* base, int64_t stride, int nsrc, int skip,
                      int64_t len, void* out, int op, int init) {
  auto* o = static_cast<typename TR::S*>(out);
  parallel_for(len, kGrainElems, [&](int64_t b, int64_t e) {
    int r0 = 0;
    if (init != 0) {
      while (r0 == skip) ++r0;
      std::memcpy(
          o + b,
          reinterpret_cast<const typename TR::S*>(base + r0 * stride) + b,
          (e - b) * sizeof(typename TR::S));
      ++r0;
    }
    for (int r = r0; r < nsrc; ++r) {
      if (r == skip) continue;
      reduce_range<TR>(
          o, reinterpret_cast<const typename TR::S*>(base + r * stride), b, e,
          op);
    }
  });
}

// SIMD-cloned codec inner loops (exports wrap them in parallel_for).
// bf16 both ways and the int8/ef passes are branchless and vectorize;
// fp16 has data-dependent subnormal branches the vectorizer skips,
// but the clones cost nothing there.
HVD_SIMD_CLONES void bf16_encode_range(const float* src, uint16_t* dst,
                                       int64_t b, int64_t e) {
  // float_to_bf16 inlined as straight-line integer ops: gcc refuses
  // to vectorize the call form (the u16 select mid-function defeats
  // its analysis) but takes this shape at every ISA width.
  for (int64_t i = b; i < e; ++i) {
    uint32_t x;
    std::memcpy(&x, src + i, 4);
    uint32_t lsb = (x >> 16) & 1u;
    uint32_t r = (x + 0x7FFFu + lsb) >> 16;
    uint32_t canon = 0x7FC0u | ((x >> 16) & 0x8000u);
    uint32_t nan = (x & 0x7FFFFFFFu) > 0x7F800000u;
    dst[i] = static_cast<uint16_t>(nan ? canon : r);
  }
}

HVD_SIMD_CLONES void bf16_decode_range(const uint16_t* src, float* dst,
                                       int64_t b, int64_t e) {
  for (int64_t i = b; i < e; ++i) dst[i] = bf16_to_float(src[i]);
}

HVD_SIMD_CLONES void fp16_encode_range(const float* src, uint16_t* dst,
                                       int64_t b, int64_t e) {
  for (int64_t i = b; i < e; ++i) dst[i] = float_to_half(src[i]);
}

HVD_SIMD_CLONES void fp16_decode_range(const uint16_t* src, float* dst,
                                       int64_t b, int64_t e) {
  for (int64_t i = b; i < e; ++i) dst[i] = half_to_float(src[i]);
}

HVD_SIMD_CLONES float maxabs_finite_range(const float* src, int64_t b,
                                          int64_t e) {
  float m = 0.0f;
  for (int64_t i = b; i < e; ++i) {
    float a = src[i];
    if (std::isfinite(a)) {
      float t = std::fabs(a);
      if (t > m) m = t;
    }
  }
  return m;
}

HVD_SIMD_CLONES void int8_quant_range(const float* src, int8_t* q,
                                      float scale, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; ++i) {
    float r = nearbyintf(src[i] / scale);  // RNE, like np.round
    int8_t v;
    if (std::isnan(r))
      v = 0;
    else if (r > 127.0f)
      v = 127;
    else if (r < -127.0f)
      v = -127;
    else
      v = static_cast<int8_t>(r);
    q[i] = v;
  }
}

HVD_SIMD_CLONES void int8_dequant_range(const int8_t* q, float* dst,
                                        float scale, int64_t b, int64_t e) {
  for (int64_t i = b; i < e; ++i)
    dst[i] = static_cast<float>(q[i]) * scale;
}

HVD_SIMD_CLONES void ef_update_range(float* residual, const float* pre,
                                     const float* wire, int64_t b,
                                     int64_t e) {
  for (int64_t i = b; i < e; ++i) {
    float r = pre[i] - wire[i];
    residual[i] = std::isfinite(r) ? r : 0.0f;
  }
}

// dtype: 0=f32, 1=f64, 2=i32, 3=i64, 4=u8, 5=f16, 6=bf16.
#define HVD_DISPATCH_DTYPE(dtype, FN, ...)      \
  switch (dtype) {                              \
    case 0:                                     \
      FN<Plain<float>>(__VA_ARGS__);            \
      return 0;                                 \
    case 1:                                     \
      FN<Plain<double>>(__VA_ARGS__);           \
      return 0;                                 \
    case 2:                                     \
      FN<Plain<int32_t>>(__VA_ARGS__);          \
      return 0;                                 \
    case 3:                                     \
      FN<Plain<int64_t>>(__VA_ARGS__);          \
      return 0;                                 \
    case 4:                                     \
      FN<Plain<uint8_t>>(__VA_ARGS__);          \
      return 0;                                 \
    case 5:                                     \
      FN<Half>(__VA_ARGS__);                    \
      return 0;                                 \
    case 6:                                     \
      FN<Bf16>(__VA_ARGS__);                    \
      return 0;                                 \
    default:                                    \
      return -1;                                \
  }

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// k-way elementwise reduction (star data plane). Returns 0 on success,
// -1 on bad dtype/op.
int hvd_reduce(const void** srcs, int nsrc, int64_t len, void* out, int dtype,
               int op) {
  if (nsrc <= 0 || op < 0 || op > 3 || len < 0) return -1;
  HVD_DISPATCH_DTYPE(dtype, reduce_kway_t, srcs, nsrc, len, out, op);
}

// In-place segment reduce: tgt op= src. The ring's recv+reduce step.
int hvd_reduce_into(void* tgt, const void* src, int64_t len, int dtype,
                    int op) {
  if (op < 0 || op > 3 || len < 0) return -1;
  HVD_DISPATCH_DTYPE(dtype, reduce_into_t, tgt, src, len, op);
}

// Fused strided gather-reduce over arena deposit slots (see above).
int hvd_reduce_strided(const void* base, int64_t stride_bytes, int nsrc,
                       int skip, int64_t len, void* out, int dtype, int op,
                       int init) {
  if (nsrc <= 0 || op < 0 || op > 3 || len < 0 || stride_bytes < 0) return -1;
  if (init != 0) {
    int first = (skip == 0) ? 1 : 0;
    if (first >= nsrc) return -1;  // nothing to seed from
  }
  HVD_DISPATCH_DTYPE(dtype, reduce_strided_t,
                     static_cast<const uint8_t*>(base), stride_bytes, nsrc,
                     skip, len, out, op, init);
}

// ---------------------------------------------------------------------------
// Fusion buffer pack/unpack (ref: MemcpyIn/OutFusionBuffer).
int hvd_pack(const void** srcs, const int64_t* nbytes, int n, void* dst) {
  if (n < 0) return -1;
  std::vector<int64_t> offs(n + 1, 0);
  for (int i = 0; i < n; ++i) {
    if (nbytes[i] < 0) return -1;
    offs[i + 1] = offs[i] + nbytes[i];
  }
  char* d = static_cast<char*>(dst);
  Pool* p = (offs[n] >= kParallelThresholdBytes && n > 1) ? pool() : nullptr;
  bool threaded = false;
  if (p != nullptr && p->workers() > 0) {
    std::function<void(int)> job = [&](int i) {
      std::memcpy(d + offs[i], srcs[i], nbytes[i]);
    };
    threaded = p->try_run(n, job);
  }
  if (!threaded)
    for (int i = 0; i < n; ++i) std::memcpy(d + offs[i], srcs[i], nbytes[i]);
  return 0;
}

int hvd_unpack(const void* src, const int64_t* nbytes, int n, void** dsts) {
  if (n < 0) return -1;
  int64_t off = 0;
  for (int i = 0; i < n; ++i) {
    if (nbytes[i] < 0) return -1;
    std::memcpy(dsts[i], static_cast<const char*>(src) + off, nbytes[i]);
    off += nbytes[i];
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Wire codec passes (common/compression.py fallbacks define the wire
// contract; these are bit-identical, GIL-free, pooled).

int hvd_bf16_encode(const float* src, int64_t n, uint16_t* dst) {
  if (n < 0) return -1;
  parallel_for(n, kGrainElems, [&](int64_t b, int64_t e) {
    bf16_encode_range(src, dst, b, e);
  });
  return 0;
}

int hvd_bf16_decode(const uint16_t* src, int64_t n, float* dst) {
  if (n < 0) return -1;
  parallel_for(n, kGrainElems, [&](int64_t b, int64_t e) {
    bf16_decode_range(src, dst, b, e);
  });
  return 0;
}

int hvd_fp16_encode(const float* src, int64_t n, uint16_t* dst) {
  if (n < 0) return -1;
  parallel_for(n, kGrainElems, [&](int64_t b, int64_t e) {
    fp16_encode_range(src, dst, b, e);
  });
  return 0;
}

int hvd_fp16_decode(const uint16_t* src, int64_t n, float* dst) {
  if (n < 0) return -1;
  parallel_for(n, kGrainElems, [&](int64_t b, int64_t e) {
    fp16_decode_range(src, dst, b, e);
  });
  return 0;
}

// int8 with a little-endian f32 scale header at dst[0:4], then n
// quantized bytes: scale = max|finite|/127 (f64 divide, stored f32 —
// the exact arithmetic of Int8Codec.encode), q = clip(rne(a/scale)),
// nan -> 0, +/-inf -> +/-127.
int hvd_int8_encode(const float* src, int64_t n, uint8_t* dst) {
  if (n < 0) return -1;
  std::atomic<uint32_t> maxbits{0};  // non-negative floats order as ints
  parallel_for(n, kGrainElems, [&](int64_t b, int64_t e) {
    uint32_t mb = float_to_bits(maxabs_finite_range(src, b, e));
    uint32_t cur = maxbits.load(std::memory_order_relaxed);
    while (mb > cur &&
           !maxbits.compare_exchange_weak(cur, mb, std::memory_order_relaxed))
      ;
  });
  float maxabs = bits_to_float(maxbits.load(std::memory_order_relaxed));
  double scale_d =
      static_cast<double>(maxabs) / 127.0;
  float scale = (std::isfinite(scale_d) && scale_d > 0.0)
                    ? static_cast<float>(scale_d)
                    : 0.0f;
  std::memcpy(dst, &scale, 4);  // LE on every supported host
  int8_t* q = reinterpret_cast<int8_t*>(dst + 4);
  if (!(std::isfinite(scale_d) && scale_d > 0.0)) {
    std::memset(q, 0, static_cast<size_t>(n));
    return 0;
  }
  parallel_for(n, kGrainElems, [&](int64_t b, int64_t e) {
    int8_quant_range(src, q, scale, b, e);
  });
  return 0;
}

int hvd_int8_decode(const uint8_t* src, int64_t n, float* dst) {
  if (n < 0) return -1;
  float scale;
  std::memcpy(&scale, src, 4);
  const int8_t* q = reinterpret_cast<const int8_t*>(src + 4);
  parallel_for(n, kGrainElems, [&](int64_t b, int64_t e) {
    int8_dequant_range(q, dst, scale, b, e);
  });
  return 0;
}

// Error-feedback residual: residual = pre - wire, non-finite lanes
// reset to 0 (ErrorFeedback.update's saturation defense).
int hvd_ef_update(float* residual, const float* pre, const float* wire,
                  int64_t n) {
  if (n < 0) return -1;
  parallel_for(n, kGrainElems, [&](int64_t b, int64_t e) {
    ef_update_range(residual, pre, wire, b, e);
  });
  return 0;
}

// ---------------------------------------------------------------------------
// Adasum (ref: adasum.h:100-280). vecs: nvec pointers to f64 arrays of
// length n, combined IN PLACE so that every slot holds the Adasum result.
// nvec must be a power of two. Dot/norm accumulation is f64 end-to-end
// like the reference's DispatchComputeDotAndNormSqrds.
int hvd_adasum(double** vecs, int nvec, int64_t n) {
  if (nvec <= 0 || (nvec & (nvec - 1)) != 0) return -1;
  std::vector<std::vector<double>> scratch(nvec);
  for (int stride = 1; stride < nvec; stride <<= 1) {
    // Each unordered pair (i, i^stride) combines symmetrically.
    for (int i = 0; i < nvec; ++i) {
      int j = i ^ stride;
      if (j < i) continue;
      const double* a = vecs[i];
      const double* b = vecs[j];
      double dot = 0.0, na = 0.0, nb = 0.0;
      for (int64_t k = 0; k < n; ++k) {
        dot += a[k] * b[k];
        na += a[k] * a[k];
        nb += b[k] * b[k];
      }
      double ca = na > 0 ? 1.0 - dot / (2.0 * na) : 1.0;
      double cb = nb > 0 ? 1.0 - dot / (2.0 * nb) : 1.0;
      auto& tmp = scratch[i];
      tmp.resize(n);
      parallel_for(n, kGrainElems, [&](int64_t b0, int64_t e0) {
        for (int64_t k = b0; k < e0; ++k) tmp[k] = ca * a[k] + cb * b[k];
      });
      std::memcpy(vecs[i], tmp.data(), n * sizeof(double));
      std::memcpy(vecs[j], tmp.data(), n * sizeof(double));
    }
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Bit-vector ops (ref: response_cache.h). op: 0=and, 1=or.
void hvd_words_op(uint64_t* acc, const uint64_t* other, int n, int op) {
  if (op == 0)
    for (int i = 0; i < n; ++i) acc[i] &= other[i];
  else
    for (int i = 0; i < n; ++i) acc[i] |= other[i];
}

// Worker threads the pool runs with (callers add themselves on top).
int hvd_threads() { return (pool() != nullptr ? pool()->workers() : 0) + 1; }

int hvd_abi_version() { return 2; }

}  // extern "C"
