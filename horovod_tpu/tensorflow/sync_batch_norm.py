"""Cross-rank synchronized batch normalization for Keras models
(ref: horovod/tensorflow/sync_batch_norm.py:22-65 — allreduce of the
batch mean and variance so every rank normalizes with global statistics).
"""
from __future__ import annotations


def _keras():
    import keras

    return keras


class SyncBatchNormalization:
    """Factory returning a keras BatchNormalization-compatible layer
    whose training-time moments are averaged across ranks.

    The reference subclasses tf BatchNormalization and overrides
    `_calculate_mean_and_var` (ref: sync_batch_norm.py:35-64). Keras 3
    has no such hook, so this builds a fresh layer with the same
    parameter surface computing BN explicitly; moments go through
    hvd.allreduce(AVERAGE) and E[x²]−E[x]² like the reference.
    """

    def __new__(cls, **kwargs):
        keras = _keras()
        from . import allreduce, size
        from ..common.types import ReduceOp

        class _SyncBN(keras.layers.Layer):
            def __init__(self, axis=-1, momentum=0.99, epsilon=1e-3,
                         center=True, scale=True, **kw):
                super().__init__(**kw)
                self.axis = axis
                self.momentum = momentum
                self.epsilon = epsilon
                self.center = center
                self.scale = scale

            def build(self, input_shape):
                dim = input_shape[self.axis]
                shape = (dim,)
                if self.scale:
                    self.gamma = self.add_weight(
                        name="gamma", shape=shape, initializer="ones")
                if self.center:
                    self.beta = self.add_weight(
                        name="beta", shape=shape, initializer="zeros")
                self.moving_mean = self.add_weight(
                    name="moving_mean", shape=shape, initializer="zeros",
                    trainable=False)
                self.moving_variance = self.add_weight(
                    name="moving_variance", shape=shape, initializer="ones",
                    trainable=False)

            def call(self, x, training=False):
                import tensorflow as tf

                ndim = len(x.shape)
                axis = self.axis % ndim
                red = [i for i in range(ndim) if i != axis]
                if training and size() > 1:
                    # Global moments: average E[x] and E[x²] across
                    # ranks, then var = E[x²] − E[x]²
                    # (ref: sync_batch_norm.py:40-58).
                    mean = tf.reduce_mean(x, axis=red)
                    sq = tf.reduce_mean(tf.square(x), axis=red)
                    mean = allreduce(mean, op=ReduceOp.AVERAGE,
                                     name=f"sbn.{self.name}.mean")
                    sq = allreduce(sq, op=ReduceOp.AVERAGE,
                                   name=f"sbn.{self.name}.sq")
                    var = sq - tf.square(mean)
                elif training:
                    mean, var = tf.nn.moments(x, axes=red)
                else:
                    mean, var = self.moving_mean, self.moving_variance
                if training:
                    self.moving_mean.assign(
                        self.moving_mean * self.momentum
                        + mean * (1.0 - self.momentum))
                    self.moving_variance.assign(
                        self.moving_variance * self.momentum
                        + var * (1.0 - self.momentum))
                shape = [1] * ndim
                shape[axis] = -1
                inv = tf.math.rsqrt(var + self.epsilon)
                out = (x - tf.reshape(mean, shape)) * tf.reshape(inv, shape)
                if self.scale:
                    out = out * tf.reshape(self.gamma, shape)
                if self.center:
                    out = out + tf.reshape(self.beta, shape)
                return out

        return _SyncBN(**kwargs)
