"""State broadcast helpers for the TF binding
(ref: horovod/tensorflow/functions.py:47-160)."""
from __future__ import annotations

from typing import Optional

import numpy as np


def broadcast_variables(variables, root_rank: int = 0):
    """Assign every variable its root-rank value in place
    (ref: functions.py:47-64 broadcast_variables)."""
    from . import broadcast

    for i, var in enumerate(variables):
        name = getattr(var, "name", None) or f"var.{i}"
        value = broadcast(var, root_rank,
                          name=f"bv.{name.replace(':', '_')}.{i}")
        var.assign(value)


def broadcast_object(obj=None, root_rank: int = 0,
                     name: Optional[str] = None):
    """Pickle-broadcast an arbitrary object (ref: functions.py:82-120)."""
    from ..common.functions import broadcast_object as _bo

    return _bo(obj, root_rank=root_rank, name=name)


def broadcast_object_fn(root_rank: int = 0, name: Optional[str] = None):
    """(ref: functions.py:122-133)"""

    def fn(obj=None):
        return broadcast_object(obj, root_rank=root_rank, name=name)

    return fn


def allgather_object(obj, name: Optional[str] = None):
    """(ref: functions.py:136-160)"""
    from ..common.functions import allgather_object as _ao

    return _ao(obj, name=name)
