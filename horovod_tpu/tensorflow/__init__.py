"""`horovod_tpu.tensorflow` — drop-in surface of `horovod.tensorflow`
(ref: horovod/tensorflow/__init__.py, horovod/tensorflow/mpi_ops.py).

    import horovod_tpu.tensorflow as hvd
    hvd.init()
    tape = hvd.DistributedGradientTape(tape)
    hvd.broadcast_variables(model.variables, root_rank=0)

Tensors ride the same asynchronous name-negotiated engine as the JAX
eager path and the torch adapter (numpy bridge). Ops are graph-safe:
under `tf.function` they trace through `tf.py_function`, with custom
gradients mirroring the reference's registered grads
(ref: horovod/tensorflow/mpi_ops.py:139-220). On TPU hardware the JAX
path is the performance surface — this adapter exists for capability
parity and CPU-cluster jobs, like the torch one.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..common.basics import (  # noqa: F401  (re-exported API surface)
    cross_rank,
    cross_size,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    metrics,
    mpi_built,
    gloo_built,
    nccl_built,
    ccl_built,
    check_extension,
    check_num_rank_power_of_2,
    cuda_built,
    ddl_built,
    gloo_enabled,
    gpu_available,
    mpi_enabled,
    mpi_threads_supported,
    num_rank_is_power_2,
    rocm_built,
    rank,
    shutdown,
    size,
)
from ..common import basics as _basics
from ..common.exceptions import HorovodInternalError
from ..common.types import Adasum, Average, ReduceOp, Sum  # noqa: F401
from .compression import Compression  # noqa: F401
from .functions import (  # noqa: F401
    allgather_object,
    broadcast_object,
    broadcast_variables,
)
from .sync_batch_norm import SyncBatchNormalization  # noqa: F401


def _tf():
    import tensorflow as tf

    return tf


def _engine():
    eng = _basics.engine()
    if eng is None:
        raise HorovodInternalError(
            "horovod_tpu.tensorflow collectives need process mode "
            "(hvdrun) or size()==1"
        )
    return eng


def _engine_allreduce(arr, nm, rop, prescale, postscale):
    eng = _engine()
    return eng.synchronize(eng.enqueue_allreduce(
        arr, name=nm, op=rop, prescale=prescale, postscale=postscale))


def _engine_grouped_allreduce(arrs, names, rop, prescale, postscale):
    """Enqueue EVERY tensor before awaiting ANY result, so the whole
    group negotiates in the same engine cycle(s) and tensor fusion can
    pack it into one wire payload (ref: AsyncOpKernel concurrency,
    tensorflow/mpi_ops.cc:371-416; fusion, controller.cc:686-809)."""
    eng = _engine()
    handles = [
        eng.enqueue_allreduce(a, name=n, op=rop, prescale=prescale,
                              postscale=postscale)
        for a, n in zip(arrs, names)
    ]
    return [eng.synchronize(h) for h in handles]


def _engine_allgather(arr, nm):
    eng = _engine()
    return eng.synchronize(eng.enqueue_allgather(arr, name=nm))


def _engine_broadcast(arr, root_rank, nm):
    eng = _engine()
    return eng.synchronize(eng.enqueue_broadcast(arr, root_rank, name=nm))


def _engine_alltoall(arr, splits, nm):
    eng = _engine()
    return eng.synchronize(eng.enqueue_alltoall(arr, splits, name=nm))


def _resolve_op(op: Optional[ReduceOp], average: Optional[bool]) -> ReduceOp:
    if op is not None and average is not None:
        raise ValueError("specify op= or the legacy average=, not both")
    if op is None:
        return ReduceOp.AVERAGE if (average is None or average) else ReduceOp.SUM
    return op


_SINGLETON_WARN_THRESHOLD = 8


def _warn_singleton_collectives_in_trace():
    """N singleton collectives inside ONE tf.function each become their
    own stateful py_function, which TF2's auto-control-dependencies
    chain in program order — N serialized engine cycles. Only the
    grouped path escapes (see grouped_allreduce). Warn once per trace
    when a function crosses the threshold, pointing users there
    (docs/frameworks.md: "The singleton-collective trap"). The counter
    lives ON the FuncGraph (not a module dict keyed by id()): it dies
    with the graph, and a recycled id can't inherit a stale count."""
    tf = _tf()
    if tf.executing_eagerly():
        return
    try:
        g = tf.compat.v1.get_default_graph()
    except Exception:
        return
    n = getattr(g, "_hvd_singleton_collectives", 0) + 1
    try:
        g._hvd_singleton_collectives = n
    except AttributeError:
        return
    if n == _SINGLETON_WARN_THRESHOLD:
        import warnings

        warnings.warn(
            f"{n}+ singleton horovod collectives traced inside one "
            "tf.function: each becomes a stateful py_function that "
            "TF2 auto-control-deps serialize (one engine cycle per "
            "tensor). Use hvd.grouped_allreduce / "
            "DistributedGradientTape / DistributedOptimizer, which "
            "negotiate the whole list in a single cycle.",
            stacklevel=3,
        )


def _eager_or_py_function(numpy_fn, tensor, out_dtype, out_shape, name):
    """Run `numpy_fn` on the tensor's value: directly when eager,
    through tf.py_function when tracing (the reference's AsyncOpKernel
    registration point, ref: tensorflow/mpi_ops.cc:371-416)."""
    tf = _tf()
    if tf.executing_eagerly():
        return tf.convert_to_tensor(numpy_fn(tensor.numpy()), dtype=out_dtype)
    _warn_singleton_collectives_in_trace()
    out = tf.py_function(
        lambda t: tf.convert_to_tensor(numpy_fn(t.numpy()), dtype=out_dtype),
        inp=[tensor],
        Tout=out_dtype,
        name=name,
    )
    out.set_shape(out_shape)
    return out


# ---------------------------------------------------------------------------
# Collectives (ref: horovod/tensorflow/__init__.py:52-201 allreduce;
# mpi_ops.py _allreduce/allgather/broadcast/alltoall)


def allreduce(
    tensor,
    average=None,
    name: Optional[str] = None,
    op: Optional[ReduceOp] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    compression=None,
):
    """All-reduce a tf.Tensor/Variable across ranks. Sparse
    tf.IndexedSlices take the allgather path like the reference
    (ref: horovod/tensorflow/__init__.py:76-106)."""
    tf = _tf()
    if isinstance(tensor, tf.IndexedSlices):
        # Average of gathered slices (ref: __init__.py:84-101).
        rop = _resolve_op(op, average)
        if rop not in (ReduceOp.SUM, ReduceOp.AVERAGE):
            raise NotImplementedError(
                "IndexedSlices allreduce supports SUM/AVERAGE only"
            )
        values = allgather(tensor.values, name=f"{name or 'ar'}.values")
        indices = allgather(tensor.indices, name=f"{name or 'ar'}.indices")
        if rop == ReduceOp.AVERAGE:
            values = values / size()
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)

    rop = _resolve_op(op, average)
    comp = compression or Compression.none
    tensor = tf.convert_to_tensor(tensor)
    compressed, ctx = comp.compress(tensor)

    if _basics.size() == 1:
        out = compressed
        f = prescale_factor * postscale_factor
        if f != 1.0:
            out = tf.cast(tf.cast(out, tf.float64) * f, out.dtype)
        return comp.decompress(out, ctx)

    nm = name or f"HorovodAllreduce_{_auto_name(tensor)}"

    def run(arr):
        return _engine_allreduce(arr, nm, rop, prescale_factor,
                                 postscale_factor)

    @tf.custom_gradient
    def op_with_grad(x):
        y = _eager_or_py_function(run, x, x.dtype, x.shape, "HorovodAllreduce")

        def grad(dy):
            # Gradient of allreduce is allreduce with the same op
            # (ref: mpi_ops.py:139-152).
            return allreduce(dy, op=rop, name=f"{nm}.grad")

        return y, grad

    return comp.decompress(op_with_grad(compressed), ctx)


_name_counter = [0]


def _auto_name(tensor) -> str:
    tname = getattr(tensor, "name", None)
    if tname and not _tf().executing_eagerly():
        return tname.replace(":", "_").replace("/", "_")
    _name_counter[0] += 1
    return f"t{_name_counter[0]}"


def grouped_allreduce(tensors, average=None, name=None, op=None,
                      prescale_factor=1.0, postscale_factor=1.0,
                      compression=None):
    """All-reduce a list of tensors as ONE group: every tensor is
    enqueued before any result is awaited, so all requests land in the
    same negotiation cycle and the engine's fusion buffer packs them
    into one wire payload (ref: tensorflow/mpi_ops.py grouped_allreduce,
    controller.cc:686-809). Under `tf.function` the group traces as a
    SINGLE py_function — per-tensor py_functions would be chained by
    TF2's auto-control-dependencies (stateful ops run in program order),
    re-serializing the group."""
    tf = _tf()
    rop = _resolve_op(op, average)
    base = name or "HorovodGrouped"
    tensors = list(tensors)
    if not tensors:
        return []
    if any(isinstance(t, tf.IndexedSlices) for t in tensors):
        # Sparse entries ride the allgather path individually (grouped
        # entries must be dense, like the reference); the dense rest
        # still goes through one group.
        out = [None] * len(tensors)
        dense_idx, dense = [], []
        for i, t in enumerate(tensors):
            if isinstance(t, tf.IndexedSlices):
                out[i] = allreduce(t, None, f"{base}.{i}", rop,
                                   prescale_factor, postscale_factor,
                                   compression)
            else:
                dense_idx.append(i)
                dense.append(t)
        if dense:
            for i, r in zip(dense_idx, grouped_allreduce(
                    dense, None, f"{base}.dense", rop, prescale_factor,
                    postscale_factor, compression)):
                out[i] = r
        return out

    comp = compression or Compression.none
    dense = [tf.convert_to_tensor(t) for t in tensors]
    pairs = [comp.compress(t) for t in dense]
    compressed = [p[0] for p in pairs]
    ctxs = [p[1] for p in pairs]

    if _basics.size() == 1:
        f = prescale_factor * postscale_factor
        if f != 1.0:
            # Scale through float64 and cast back, like the engine's
            # _scale_np — int * python float must not upcast/raise.
            compressed = [tf.cast(tf.cast(t, tf.float64) * f, t.dtype)
                          for t in compressed]
        return [comp.decompress(o, c) for o, c in zip(compressed, ctxs)]

    names = [f"{base}.{i}" for i in range(len(compressed))]

    def run_group(arrs):
        return _engine_grouped_allreduce(
            arrs, names, rop, prescale_factor, postscale_factor)

    @tf.custom_gradient
    def op_with_grad(*xs):
        if tf.executing_eagerly():
            outs = run_group([x.numpy() for x in xs])
            ys = [tf.convert_to_tensor(o, dtype=x.dtype)
                  for o, x in zip(outs, xs)]
        else:
            dtypes = [x.dtype for x in xs]

            def py_run(*ts):
                outs = run_group([t.numpy() for t in ts])
                return [tf.convert_to_tensor(o, dtype=d)
                        for o, d in zip(outs, dtypes)]

            ys = tf.py_function(py_run, inp=list(xs), Tout=dtypes,
                                name="HorovodGroupedAllreduce")
            if len(xs) == 1:
                ys = [ys] if not isinstance(ys, (list, tuple)) else list(ys)
            for y, x in zip(ys, xs):
                y.set_shape(x.shape)

        def grad(*dys):
            # Gradient of a grouped allreduce is a grouped allreduce of
            # the cotangents with the same op (ref: mpi_ops.py:139-152).
            return grouped_allreduce(list(dys), op=rop, name=f"{base}.grad",
                                     compression=compression)

        return ys, grad

    outs = op_with_grad(*compressed)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    return [comp.decompress(o, c) for o, c in zip(outs, ctxs)]


def allgather(tensor, name: Optional[str] = None):
    """Concatenate across ranks on dim 0; first dims may differ
    (ref: mpi_ops.py allgather, collective_operations.h:206-256)."""
    tf = _tf()
    tensor = tf.convert_to_tensor(tensor)
    if _basics.size() == 1:
        return tf.identity(tensor)
    nm = name or f"HorovodAllgather_{_auto_name(tensor)}"

    def run(arr):
        return _engine_allgather(arr, nm)

    @tf.custom_gradient
    def op_with_grad(x):
        out_shape = tf.TensorShape([None] + list(x.shape)[1:])
        y = _eager_or_py_function(run, x, x.dtype, out_shape,
                                  "HorovodAllgather")

        def grad(dy):
            # Sum the grad across ranks, then take this rank's slice
            # (ref: mpi_ops.py:154-186).
            summed = allreduce(dy, op=ReduceOp.SUM, name=f"{nm}.grad")
            sizes = allgather(
                tf.convert_to_tensor([tf.shape(x)[0]]), name=f"{nm}.gsizes"
            )
            offset = tf.reduce_sum(sizes[: rank()])
            return summed[offset : offset + tf.shape(x)[0]]

        return y, grad

    return op_with_grad(tensor)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    tf = _tf()
    tensor = tf.convert_to_tensor(tensor)
    if _basics.size() == 1:
        return tf.identity(tensor)
    nm = name or f"HorovodBroadcast_{_auto_name(tensor)}"

    def run(arr):
        return _engine_broadcast(arr, root_rank, nm)

    @tf.custom_gradient
    def op_with_grad(x):
        y = _eager_or_py_function(run, x, x.dtype, x.shape,
                                  "HorovodBroadcast")

        def grad(dy):
            # Reduce grads to the root; zero elsewhere
            # (ref: mpi_ops.py:188-200).
            summed = allreduce(dy, op=ReduceOp.SUM, name=f"{nm}.grad")
            if rank() == root_rank:
                return summed
            return tf.zeros_like(summed)

        return y, grad

    return op_with_grad(tensor)


def alltoall(tensor, splits=None, name: Optional[str] = None):
    """(ref: mpi_ops.py alltoall) Returns (output, received_splits)."""
    tf = _tf()
    tensor = tf.convert_to_tensor(tensor)
    if _basics.size() == 1:
        s = splits if splits is not None else [int(tensor.shape[0])]
        return tf.identity(tensor), tf.convert_to_tensor(list(s))
    nm = name or f"HorovodAlltoall_{_auto_name(tensor)}"
    # splits may be a python list, an eager tensor, or (inside the
    # traced backward) a symbolic tensor; symbolic splits resolve inside
    # the py_function where execution is eager. -1 marks "even split".
    if splits is None:
        splits_t = tf.fill([_basics.size()], -1)
    else:
        splits_t = tf.cast(tf.convert_to_tensor(splits), tf.int32)

    def _run_np(arr, split_arr):
        sl = [int(s) for s in split_arr.tolist()]
        if sl and sl[0] < 0:
            sl = None
        return _engine_alltoall(arr, sl, nm)

    @tf.custom_gradient
    def op_with_grad(x, s):
        if tf.executing_eagerly():
            out, recv = _run_np(x.numpy(), s.numpy())
            out = tf.convert_to_tensor(out)
            recv = tf.convert_to_tensor(np.asarray(recv, np.int32))
        else:
            def run(t, st):
                o, r = _run_np(t.numpy(), st.numpy())
                return (tf.convert_to_tensor(o),
                        tf.convert_to_tensor(np.asarray(r, np.int32)))

            out, recv = tf.py_function(
                run, inp=[x, s], Tout=[x.dtype, tf.int32],
                name="HorovodAlltoall",
            )
            out.set_shape(tf.TensorShape([None] + list(x.shape)[1:]))
            recv.set_shape(tf.TensorShape([_basics.size()]))

        def grad(dy, drecv=None):
            # Backward of alltoall is the reverse exchange: route each
            # received block back to its sender using the received
            # splits (ref: mpi_ops.py alltoall gradient registration).
            back, _ = alltoall(dy, splits=recv, name=f"{nm}.grad")
            return back, None

        return (out, recv), grad

    return op_with_grad(tensor, splits_t)


# ---------------------------------------------------------------------------
# Async handle API (eager): the same enqueue/synchronize shape as the
# torch adapter (torch/__init__.py) and the reference's *_async ops.
# Under tf.function use grouped_allreduce instead — handles are python
# ints and cannot cross a graph trace.

from ..common.async_handles import LocalResultStore

_handles = {}
_local_results = LocalResultStore()


def _scale_preserving_dtype(arr: np.ndarray, factor: float) -> np.ndarray:
    """Scale without changing dtype (numpy int * python float would
    upcast to float64) — the engine's _scale_np, reused."""
    if factor == 1.0:
        return arr
    from ..engine.engine import _scale_np

    return _scale_np(arr, factor)


def _check_eager(api: str):
    if not _tf().executing_eagerly():
        raise RuntimeError(
            f"{api} is eager-only (handles cannot cross a tf.function "
            "trace); use grouped_allreduce inside tf.function"
        )


def allreduce_async(tensor, average=None, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0) -> int:
    """Enqueue an allreduce and return a handle immediately; redeem with
    synchronize(). (ref: tensorflow/mpi_ops.py _allreduce async kernel)"""
    _check_eager("allreduce_async")
    tf = _tf()
    rop = _resolve_op(op, average)
    t = tf.convert_to_tensor(tensor)
    arr = t.numpy()
    if _basics.size() == 1:
        h = _local_results.put(
            _scale_preserving_dtype(arr, prescale_factor * postscale_factor))
    else:
        h = _engine().enqueue_allreduce(
            arr, name=name, op=rop,
            prescale=prescale_factor, postscale=postscale_factor)
    _handles[h] = t.dtype
    return h


def allgather_async(tensor, name=None) -> int:
    _check_eager("allgather_async")
    tf = _tf()
    t = tf.convert_to_tensor(tensor)
    if _basics.size() == 1:
        h = _local_results.put(t.numpy())
    else:
        h = _engine().enqueue_allgather(t.numpy(), name=name)
    _handles[h] = t.dtype
    return h


def broadcast_async(tensor, root_rank, name=None) -> int:
    _check_eager("broadcast_async")
    tf = _tf()
    t = tf.convert_to_tensor(tensor)
    if _basics.size() == 1:
        h = _local_results.put(t.numpy())
    else:
        h = _engine().enqueue_broadcast(t.numpy(), root_rank, name=name)
    _handles[h] = t.dtype
    return h


def poll(handle: int) -> bool:
    if handle in _local_results:
        return True
    if handle < 0:
        return False
    return _engine().poll(handle)


def synchronize(handle: int):
    """Block until the handle's collective completes; returns the
    result as a tf.Tensor (ref: mpi_ops.py synchronize)."""
    dtype = _handles.pop(handle, None)
    if handle in _local_results:
        out = _local_results.pop(handle)
    elif handle < 0:
        # Negative handles never reach the engine; falling through
        # would surface as an opaque engine KeyError.
        raise ValueError(
            f"handle {handle} was already synchronized (results are "
            "consumed on first synchronize)"
        )
    else:
        out = _engine().synchronize(handle)
    return _tf().convert_to_tensor(np.asarray(out), dtype=dtype)


def _dynamic_int_op(fn, name: str):
    """An int op whose value is read at EXECUTION time, not trace time
    (ref: tensorflow/mpi_ops.py rank_op/size_op — the reference's
    kernels read the controller's current value so a traced function
    sees post-elastic-reset topology)."""
    tf = _tf()
    out = tf.py_function(lambda: np.int32(fn()), inp=[], Tout=tf.int32,
                         name=name)
    out.set_shape(())
    return out


def rank_op(name=None):
    return _dynamic_int_op(_basics.rank, name or "HorovodRank")


def local_rank_op(name=None):
    return _dynamic_int_op(_basics.local_rank, name or "HorovodLocalRank")


def size_op(name=None):
    return _dynamic_int_op(_basics.size, name or "HorovodSize")


def local_size_op(name=None):
    return _dynamic_int_op(_basics.local_size, name or "HorovodLocalSize")


def join() -> int:
    from ..ops import join as _join

    return _join()


def barrier():
    from ..ops import barrier as _barrier

    _barrier()


# ---------------------------------------------------------------------------
# Gradient aggregation helpers (ref: horovod/tensorflow/__init__.py:242-287)


def _make_allreduce_grads_fn(name_scope: str, device_dense, device_sparse,
                             compression, sparse_as_dense, op,
                             gradient_predivide_factor: float = 1.0):
    """Returns grads_fn(list) -> list, splitting AVERAGE into
    pre/postscale divisions like the reference when a predivide factor
    is given (ref: __init__.py:242-274)."""
    tf = _tf()

    if op == ReduceOp.AVERAGE and gradient_predivide_factor != 1.0:
        # Divide average into pre- and post-scale factors.
        prescale = 1.0 / gradient_predivide_factor
        postscale = gradient_predivide_factor / size()
        eff_op = ReduceOp.SUM
    else:
        prescale, postscale, eff_op = 1.0, 1.0, op

    def allreduce_grads(grads):
        # All non-None gradients go through ONE grouped allreduce so the
        # whole list negotiates in the same engine cycle and fusion
        # fires (N serial allreduces would pay ≥1 cycle each);
        # grouped_allreduce itself routes any remaining IndexedSlices
        # down the allgather path.
        out = [None] * len(grads)
        idx = [i for i, g in enumerate(grads) if g is not None]
        batch = []
        for i in idx:
            g = grads[i]
            if sparse_as_dense and isinstance(g, tf.IndexedSlices):
                g = tf.convert_to_tensor(g)
            batch.append(g)
        if batch:
            reduced = grouped_allreduce(
                batch,
                op=eff_op,
                name=f"{name_scope}.grads",
                prescale_factor=prescale,
                postscale_factor=postscale,
                compression=compression,
            )
            for i, r in zip(idx, reduced):
                out[i] = r
        return out

    return allreduce_grads


class DistributedGradientTape:
    """Wrap tf.GradientTape so .gradient() allreduces
    (ref: horovod/tensorflow/__init__.py:434-505 _DistributedGradientTape,
    :507-572 factory)."""

    def __init__(self, gradtape, device_dense="", device_sparse="",
                 compression=None, sparse_as_dense=False, op=ReduceOp.AVERAGE,
                 gradient_predivide_factor: float = 1.0):
        self._tape = gradtape
        self._allreduce_grads = _make_allreduce_grads_fn(
            "DistributedGradientTape", device_dense, device_sparse,
            compression or Compression.none, sparse_as_dense, op,
            gradient_predivide_factor,
        )

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def gradient(self, target, sources, output_gradients=None):
        tf = _tf()
        grads = self._tape.gradient(target, sources, output_gradients)
        # Sources may be a tensor, list, dict, or nested structure
        # (the reference flattens with tf.nest the same way).
        flat = tf.nest.flatten(grads)
        return tf.nest.pack_sequence_as(grads, self._allreduce_grads(flat))


def DistributedOptimizer(
    optimizer,
    name: Optional[str] = None,
    device_dense: str = "",
    device_sparse: str = "",
    compression=None,
    sparse_as_dense: bool = False,
    backward_passes_per_step: int = 1,
    op: ReduceOp = ReduceOp.AVERAGE,
    gradient_predivide_factor: float = 1.0,
):
    """Wrap a tf.compat.v1.train.Optimizer or a Keras optimizer so
    gradients are allreduced before updates
    (ref: horovod/tensorflow/__init__.py:289-332 for v1 optimizers; the
    keras wrap lives in horovod_tpu.keras like the reference's
    horovod/_keras/__init__.py:27-143)."""
    tf = _tf()
    if isinstance(optimizer, tf.compat.v1.train.Optimizer):
        return _make_v1_optimizer(
            optimizer, name, device_dense, device_sparse, compression,
            sparse_as_dense, op, gradient_predivide_factor,
            backward_passes_per_step,
        )
    from ..keras import DistributedOptimizer as _keras_wrap

    return _keras_wrap(
        optimizer,
        compression=compression,
        sparse_as_dense=sparse_as_dense,
        backward_passes_per_step=backward_passes_per_step,
        op=op,
        gradient_predivide_factor=gradient_predivide_factor,
    )


def _make_v1_optimizer(optimizer, name, device_dense, device_sparse,
                       compression, sparse_as_dense, op,
                       gradient_predivide_factor,
                       backward_passes_per_step: int = 1):
    tf = _tf()

    if op == ReduceOp.ADASUM and size() > 1:
        return _make_v1_adasum_optimizer(
            optimizer, name, device_dense, device_sparse,
            compression or Compression.none, sparse_as_dense,
            int(backward_passes_per_step),
        )

    allreduce_grads = _make_allreduce_grads_fn(
        name or f"Distributed{type(optimizer).__name__}", device_dense,
        device_sparse, compression or Compression.none, sparse_as_dense,
        op, gradient_predivide_factor,
    )

    class _DistributedOptimizer(type(optimizer)):
        """Dynamic subclass overriding compute_gradients
        (ref: __init__.py:289-332)."""

        def __init__(self):
            # Alias (not copy) the wrapped instance's state so
            # post-wrap mutations of the original optimizer (e.g. its
            # learning rate) reach the wrapper, matching the torch and
            # Keras surfaces.
            object.__setattr__(self, "__dict__", optimizer.__dict__)

        def compute_gradients(self, *args, **kwargs):
            gradients = type(optimizer).compute_gradients(
                self, *args, **kwargs
            )
            grads, variables = zip(*gradients)
            reduced = allreduce_grads(list(grads))
            return list(zip(reduced, variables))

    _DistributedOptimizer.__name__ = f"Distributed{type(optimizer).__name__}"
    return _DistributedOptimizer()


def _make_v1_adasum_optimizer(optimizer, name, device_dense, device_sparse,
                              compression, sparse_as_dense, k):
    """Delta-model Adasum for tf.compat.v1 optimizers
    (ref: horovod/tensorflow/__init__.py:334-428
    _DistributedAdasumOptimizer): gradients are left local; the wrapped
    optimizer applies its own step, and every k-th apply the weight
    deltas since the last communication are Adasum-combined and written
    back. Eager-mode only — the reference expresses the same schedule
    in graph mode via `_is_comm_step` tf.cond plumbing (:356,383-386),
    which has no meaningful equivalent under this engine's py_function
    bridge."""
    tf = _tf()

    allreduce_deltas = _make_allreduce_grads_fn(
        name or f"DistributedDelta{type(optimizer).__name__}", device_dense,
        device_sparse, compression, sparse_as_dense, ReduceOp.ADASUM, 1.0,
    )

    class _V1AdasumOptimizer(type(optimizer)):
        def __init__(self):
            # Alias (not copy) the wrapped optimizer's __dict__ for
            # consistency with the torch and Keras wrappers: mutating
            # the original instance after wrapping must be visible here.
            object.__setattr__(self, "__dict__", optimizer.__dict__)
            self._hvd_start = None
            self._hvd_count = 0

        # compute_gradients is inherited untouched: the combine happens
        # on weight deltas, not gradients.

        def apply_gradients(self, grads_and_vars, global_step=None,
                            name=None):
            if not tf.executing_eagerly():
                raise NotImplementedError(
                    "op=Adasum on the v1 optimizer surface requires "
                    "eager execution; use the Keras optimizer wrapper "
                    "for traced training"
                )
            gvs = list(grads_and_vars)
            tvars = [v for _, v in gvs]
            if self._hvd_start is None:
                self._hvd_start = [
                    tf.Variable(tf.convert_to_tensor(v), trainable=False)
                    for v in tvars
                ]
            result = type(optimizer).apply_gradients(
                self, gvs, global_step=global_step, name=name
            )
            self._hvd_count += 1
            if self._hvd_count % k == 0:
                deltas = [
                    tf.convert_to_tensor(v) - s
                    for v, s in zip(tvars, self._hvd_start)
                ]
                combined = allreduce_deltas(deltas)
                for v, s, d in zip(tvars, self._hvd_start, combined):
                    s.assign_add(d)
                    v.assign(s)
            return result

    _V1AdasumOptimizer.__name__ = (
        f"DistributedDelta{type(optimizer).__name__}"
    )
    return _V1AdasumOptimizer()


def broadcast_global_variables(root_rank: int = 0):
    """(ref: horovod/tensorflow/__init__.py:182-201) — v1 graph helper;
    in TF2 eager, broadcasts every tf.Variable currently tracked by the
    default strategy is not possible, so this covers the v1 path."""
    tf = _tf()
    if tf.executing_eagerly():
        raise RuntimeError(
            "broadcast_global_variables is graph-mode only; use "
            "hvd.broadcast_variables(model.variables, root_rank) in TF2"
        )
    return broadcast_variables(
        tf.compat.v1.global_variables(), root_rank=root_rank
    )


class BroadcastGlobalVariablesHook:
    """SessionRunHook equivalent (ref: __init__.py:206-239): broadcasts
    variables once after session creation. TF2-friendly shape: call
    `hook.on_train_begin(model)`."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, model):
        broadcast_variables(model.variables, root_rank=self.root_rank)


def elastic_run(fn):  # pragma: no cover - thin alias
    from ..elastic import run as _run

    return _run(fn)
