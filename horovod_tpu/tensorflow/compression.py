"""Gradient compression for the TF binding
(ref: horovod/tensorflow/compression.py:24-74).

Thin re-export of the single-source interface in
`common/compression.py` plus the TensorFlow tensor-type adapter — see
`ops/compression.py` for the layering note (framework compressors vs
the data-plane wire codecs)."""
from __future__ import annotations

from ..common.compression import Compressor, NoneCompressor

__all__ = ["Compressor", "NoneCompressor", "FP16Compressor",
           "Compression"]


class FP16Compressor(Compressor):
    """Cast float tensors to fp16 on the wire (ref: compression.py:46-64)."""

    @staticmethod
    def compress(tensor):
        import tensorflow as tf

        if tensor.dtype.is_floating:
            return tf.cast(tensor, tf.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        import tensorflow as tf

        if ctx is not None:
            return tf.cast(tensor, ctx)
        return tensor


class Compression:
    """(ref: compression.py:67-74)"""

    none = NoneCompressor
    fp16 = FP16Compressor
