"""Gradient compression for the TF binding
(ref: horovod/tensorflow/compression.py:24-74)."""
from __future__ import annotations


class Compressor:
    """Interface (ref: compression.py:24-35)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast float tensors to fp16 on the wire (ref: compression.py:46-64)."""

    @staticmethod
    def compress(tensor):
        import tensorflow as tf

        if tensor.dtype.is_floating:
            return tf.cast(tensor, tf.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        import tensorflow as tf

        if ctx is not None:
            return tf.cast(tensor, ctx)
        return tensor


class Compression:
    """(ref: compression.py:67-74)"""

    none = NoneCompressor
    fp16 = FP16Compressor
