"""Elastic state for TF/Keras models
(ref: horovod/tensorflow/elastic.py:91-210 TensorFlowKerasState).

Keeps an in-memory copy of model + optimizer variables; `sync()`
broadcasts rank 0's values after a topology change, matching the
reference's save/restore/sync contract (ref: common/elastic.py:95-109).
"""
from __future__ import annotations

import numpy as np

from ..elastic.state import ObjectState


class TensorFlowKerasState(ObjectState):
    """State wrapping a Keras model + optimizer plus scalar attributes
    like epoch/batch (ref: tensorflow/elastic.py:91-160)."""

    def __init__(self, model, optimizer=None, **kwargs):
        self.model = model
        self.optimizer = optimizer
        self._saved_model_weights = None
        self._saved_opt_weights = None
        super().__init__(**kwargs)

    def _opt_vars(self):
        if self.optimizer is None:
            return []
        v = getattr(self.optimizer, "variables", [])
        return list(v() if callable(v) else v)

    def save(self):
        self._saved_model_weights = [
            np.copy(w) for w in self.model.get_weights()
        ]
        self._saved_opt_weights = [
            np.copy(v.numpy()) for v in self._opt_vars()
        ]
        super().save()

    def restore(self):
        if self._saved_model_weights is not None:
            self.model.set_weights(self._saved_model_weights)
        for var, val in zip(self._opt_vars(), self._saved_opt_weights or []):
            var.assign(val)
        super().restore()

    def sync(self):
        from .functions import broadcast_object

        weights = broadcast_object(
            [np.asarray(w) for w in self.model.get_weights()],
            root_rank=0, name="tfks.model",
        )
        self.model.set_weights(weights)
        opt_vals = broadcast_object(
            [np.asarray(v.numpy()) for v in self._opt_vars()],
            root_rank=0, name="tfks.opt",
        )
        for var, val in zip(self._opt_vars(), opt_vals):
            if tuple(var.shape) == tuple(np.shape(val)):
                var.assign(val)
        super().sync()


KerasState = TensorFlowKerasState
