"""tf.keras callbacks (ref: horovod/tensorflow/keras/callbacks.py —
same classes as the standalone-Keras surface)."""
from ...keras.callbacks import *  # noqa: F401,F403
from ...keras.callbacks import (  # noqa: F401
    BroadcastGlobalVariablesCallback,
    MetricAverageCallback,
)
