"""`horovod_tpu.tensorflow.keras` — drop-in surface of
`horovod.tensorflow.keras` (ref: horovod/tensorflow/keras/__init__.py).

The reference ships two Keras surfaces — `horovod.keras` (standalone
Keras) and `horovod.tensorflow.keras` (tf.keras) — with identical
semantics over the same `horovod._keras` implementation. Keras 3 is
the one Keras, so this package re-exports `horovod_tpu.keras` under
the reference's tf-flavored import path; scripts written as
`import horovod.tensorflow.keras as hvd` port by renaming the package
only.
"""
from ..compression import Compression  # noqa: F401
from ...common.basics import (  # noqa: F401
    ccl_built,
    cuda_built,
    cross_rank,
    cross_size,
    ddl_built,
    gloo_built,
    gloo_enabled,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    rocm_built,
    shutdown,
    size,
)
from ...common.types import Adasum, Average, ReduceOp, Sum  # noqa: F401
from ...keras import (  # noqa: F401
    DistributedOptimizer,
    allgather,
    allgather_object,
    allreduce,
    broadcast,
    broadcast_global_variables,
    broadcast_object,
    broadcast_variables,
    barrier,
    join,
    load_model,
)
from ...keras import callbacks  # noqa: F401
from ...keras.elastic import KerasState  # noqa: F401
from . import elastic  # noqa: F401
