"""Elastic state for tf.keras (ref: horovod/tensorflow/keras/elastic.py
— KerasState over the shared implementation)."""
from ...keras.elastic import KerasState  # noqa: F401
