"""Keras model.fit MNIST with horovod_tpu.keras
(ref: examples/tensorflow2_keras_mnist.py — DistributedOptimizer +
broadcast/metric-average/LR-warmup callbacks + rank-sharded data).

Run:
    hvdrun -np 2 python examples/tensorflow2_keras_mnist.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from jax_mnist import load_mnist, synthetic_mnist  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data-dir", default=None)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.001)
    args = p.parse_args()

    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
    import keras

    import horovod_tpu.keras as hvd

    hvd.init()

    x, y = (load_mnist(args.data_dir) if args.data_dir
            else synthetic_mnist())
    x, y = x[hvd.rank()::hvd.size()], y[hvd.rank()::hvd.size()]

    model = keras.Sequential([
        keras.Input((28, 28)),
        keras.layers.Flatten(),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10, activation="softmax"),
    ])

    # Scale LR by size; warmup eases the large effective batch in
    # (ref: tensorflow2_keras_mnist.py scaled_lr + warmup callback).
    scaled_lr = args.lr * hvd.size()
    opt = hvd.DistributedOptimizer(keras.optimizers.Adam(scaled_lr))
    model.compile(
        optimizer=opt,
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
        run_eagerly=True,  # collectives are eager ops in this binding
    )

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(
            initial_lr=args.lr, warmup_epochs=1, verbose=hvd.rank() == 0),
    ]
    verbose = 1 if hvd.rank() == 0 else 0
    model.fit(x, y, batch_size=args.batch_size, epochs=args.epochs,
              callbacks=callbacks, verbose=verbose)

    if hvd.rank() == 0:
        _, acc = model.evaluate(x[:1024], y[:1024], verbose=0)
        print(f"train accuracy (first 1024): {acc:.3f}")


if __name__ == "__main__":
    main()
