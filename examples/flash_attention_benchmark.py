"""Flash-attention kernel benchmark: Pallas vs XLA dense, forward and
forward+backward, across sequence lengths (the numbers quoted in
docs/kernels.md come from this script on one v5e chip).

Run:  python examples/flash_attention_benchmark.py [--dtype bf16]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
import time

import numpy as np

import jax
import jax.numpy as jnp

from horovod_tpu.ops.flash_attention import flash_attention
from horovod_tpu.parallel.ring import dense_attention


def bench(fn, args, iters=20):
    out = fn(*args)
    first = out[0] if isinstance(out, tuple) else out
    jax.device_get(np.asarray(first).ravel()[:1])
    best = float("inf")
    for _ in range(2):  # two rounds; first can hit warmup anomalies
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        first = out[0] if isinstance(out, tuple) else out
        jax.device_get(np.asarray(first).ravel()[:1])
        best = min(best, (time.perf_counter() - t0) / iters * 1e3)
    return best


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dtype", default="bf16", choices=["bf16", "f32"])
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--iters", type=int, default=20)
    args = p.parse_args()
    dt = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    H, D = args.heads, args.head_dim
    rng = np.random.RandomState(0)

    print(f"platform={jax.devices()[0].platform} "
          f"dtype={args.dtype} H={H} D={D}")
    print(f"{'B':>3} {'S':>6} | {'fwd flash':>9} {'fwd dense':>9} "
          f"{'x':>5} | {'f+b flash':>9} {'f+b dense':>9} {'x':>5}  (ms)")
    for B, S in [(8, 512), (4, 1024), (2, 2048), (2, 4096), (1, 8192)]:
        q, k, v = (jnp.asarray(rng.randn(B, S, H, D), dt)
                   for _ in range(3))
        f_fwd = jax.jit(lambda q, k, v: flash_attention(q, k, v,
                                                        causal=True))
        d_fwd = jax.jit(lambda q, k, v: dense_attention(q, k, v,
                                                        causal=True))
        f_g = jax.jit(jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal=True).astype(jnp.float32)
            ** 2), argnums=(0, 1, 2)))
        d_g = jax.jit(jax.grad(lambda q, k, v: jnp.sum(
            dense_attention(q, k, v, causal=True).astype(jnp.float32)
            ** 2), argnums=(0, 1, 2)))
        tf_, td = bench(f_fwd, (q, k, v), args.iters), \
            bench(d_fwd, (q, k, v), args.iters)
        gf, gd = bench(f_g, (q, k, v), args.iters), \
            bench(d_g, (q, k, v), args.iters)
        print(f"{B:>3} {S:>6} | {tf_:>9.2f} {td:>9.2f} {td / tf_:>5.2f} "
              f"| {gf:>9.2f} {gd:>9.2f} {gd / gf:>5.2f}")


if __name__ == "__main__":
    main()
