"""Eager-engine allreduce micro-benchmark: latency / bandwidth vs size.

Measures the process-mode data plane the way the reference community
benchmarks Gloo vs MPI backends — per-op latency for small tensors and
achieved bus bandwidth for large ones, across the data-plane algorithms
(ref methodology: gloo ring allreduce,
horovod/common/ops/gloo_operations.cc:119-166).

Run under the launcher (2-8 processes):

    hvdrun -np 4 python examples/microbench_allreduce.py
    hvdrun -np 4 python examples/microbench_allreduce.py --algo ring
    hvdrun -np 2 python examples/microbench_allreduce.py --sizes 4194304

The default is a SWEEP: star vs single-shot ring vs segmented
(pipelined) ring — plus hierarchical ring when the launcher assigned a
multi-host topology — at 64KB / 1MB / 16MB. All the algorithm knobs
(HOROVOD_CPU_OPERATIONS, HOROVOD_RING_THRESHOLD,
HOROVOD_RING_SEGMENT_BYTES) are read per call, so one process flips
them between timed loops; every rank executes the same schedule, so
the flips stay collectively consistent. Rank 0 prints a table (GB/s)
and ONE JSON summary line.

`--mode transport` is the shared-memory acceptance measurement
(docs/running.md "Transports"): order-alternated paired rounds of the
16MB allreduce with the route flipped tcp<->shm between
barrier-separated timed loops (HOROVOD_TRANSPORT is read per call;
the overlays are established at init because this mode sets `auto`
before hvd.init()). Steady-state tensor names, so the response cache
engages and the loops measure the data plane, not negotiation.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import argparse
import json
import time


def _set_algo_env(algo, segment_bytes):
    """Flip the per-call data-plane knobs. Identical on every rank —
    the launcher gave all workers the same argv — so the ring/star
    decision stays collectively consistent mid-run."""
    if algo == "auto":
        return  # measure exactly the as-launched library defaults
    os.environ.pop("HOROVOD_CPU_OPERATIONS", None)
    os.environ["HOROVOD_RING_SEGMENT_BYTES"] = "0"
    if algo == "star":
        os.environ["HOROVOD_CPU_OPERATIONS"] = "star"
    elif algo in ("ring", "hier"):
        os.environ["HOROVOD_RING_THRESHOLD"] = "0"
    elif algo == "segring":
        os.environ["HOROVOD_RING_THRESHOLD"] = "0"
        os.environ["HOROVOD_RING_SEGMENT_BYTES"] = str(segment_bytes)


def _bench_one(hvd, np, algo, count, iters, warmup):
    x = np.ones(count, np.float32)
    for i in range(warmup):
        hvd.allreduce(x, name=f"warm.{algo}.{count}.{i}")
    hvd.barrier()
    t0 = time.perf_counter()
    for i in range(iters):
        hvd.allreduce(x, name=f"bench.{algo}.{count}.{i}")
    dt = (time.perf_counter() - t0) / iters
    n = hvd.size()
    # Bus bandwidth uses the ring-allreduce wire factor 2(n-1)/n
    # (bytes each rank moves per link), the NCCL-tests convention.
    busbw = x.nbytes * 2 * (n - 1) / n / dt
    return {"algo": algo, "bytes": x.nbytes, "lat_us": dt * 1e6,
            "busbw_GBps": busbw / 1e9}


def _percentile(sorted_vals, q):
    return sorted_vals[min(int(q * len(sorted_vals)), len(sorted_vals) - 1)]


def _bench_latency(hvd, np, basics, args):
    """Small-op enqueue-to-complete latency (p50/p99), swept over
    channel counts. Measures the engine path directly (enqueue +
    synchronize) so the number is the engine's latency, not the
    framework wrapper's. Compare a second launch with
    HOROVOD_CYCLE_EVENT_DRIVEN=0 to see the fixed-sleep floor this
    mode exists to demonstrate."""
    import os as _os
    import time as _time

    eng = basics.engine()
    x = np.ones(args.lat_count, np.float32)
    rows = []
    for nch in [1, args.channels]:
        _os.environ["HOROVOD_NUM_CHANNELS"] = str(nch)
        # Per-arm tensor name: a cached response replays the channel it
        # was negotiated with, so reusing one name across arms would
        # keep the second arm on the first arm's channel schedule.
        name = f"lat.c{nch}"
        for i in range(args.warmup):
            eng.synchronize(eng.enqueue_allreduce(x, name=name),
                            timeout=120)
        hvd.barrier()
        lats = []
        for i in range(args.iters):
            t0 = _time.perf_counter()
            eng.synchronize(eng.enqueue_allreduce(x, name=name),
                            timeout=120)
            lats.append(_time.perf_counter() - t0)
        hvd.barrier()
        lats.sort()
        rows.append({"channels": nch, "bytes": x.nbytes,
                     "p50_us": _percentile(lats, 0.5) * 1e6,
                     "p99_us": _percentile(lats, 0.99) * 1e6})
    return rows


def _bench_pipeline(hvd, np, basics, args):
    """Mixed-size pipelined workload: an async window of big allreduces
    with small allreduces interleaved (the gradient + metrics/sync-BN
    shape), channels=1 vs channels=N interleaved per round so the two
    arms see the same machine state. Fusion is disabled for the loop so
    each op is its own response and the channel schedule is what's
    measured."""
    import os as _os
    import time as _time

    eng = basics.engine()
    prev_fusion = eng.controller.fusion_threshold
    eng.controller.fusion_threshold = 1
    stream = []
    per_big = max(args.pipe_smalls // max(args.pipe_bigs, 1), 0)
    for _ in range(args.pipe_bigs):
        stream.append(args.pipe_big_count)
        stream.extend([args.pipe_small_count] * per_big)
    bufs = {n: np.ones(n, np.float32) for n in set(stream)}

    def one_round(nch, tag):
        _os.environ["HOROVOD_NUM_CHANNELS"] = str(nch)
        hvd.barrier()
        t0 = _time.perf_counter()
        handles = [
            eng.enqueue_allreduce(bufs[n], name=f"pipe.{tag}.{i}")
            for i, n in enumerate(stream)
        ]
        for h in handles:
            eng.synchronize(h, timeout=300)
        dt = _time.perf_counter() - t0
        hvd.barrier()
        return dt

    one_round(1, "w1")
    one_round(args.channels, "w2")
    pairs = [(one_round(1, f"a{r}"), one_round(args.channels, f"b{r}"))
             for r in range(args.pipe_rounds)]
    eng.controller.fusion_threshold = prev_fusion
    ratios = sorted(a / b for a, b in pairs)
    return {
        "stream_bytes": [n * 4 for n in stream],
        "channels": args.channels,
        "pairs_s": [[round(a, 4), round(b, 4)] for a, b in pairs],
        "ratios": [round(x, 3) for x in ratios],
        "median_speedup": round(_percentile(ratios, 0.5), 3),
    }


def _bench_transport(hvd, np, args, seg_bytes):
    """The shared-memory acceptance measurement: order-alternated
    paired rounds of the SAME segmented-ring schedule over tcp vs shm
    on co-located ranks (the paired-round protocol PR 3/4 used — on a
    shared box, sequential arms measure load drift, not transport
    cost). Requires launching with HOROVOD_TRANSPORT=shm/auto so the
    overlays exist (the mode sets auto itself before init); the route
    flips between barrier-separated timed loops, which is exactly the
    consistency contract the per-call knob documents."""
    import os as _os
    import time as _time

    _set_algo_env("segring", seg_bytes)
    x = np.ones(args.transport_count, np.float32)

    def timed(transport):
        # STEADY-STATE names (one per transport arm, reused every
        # iteration, like training reusing its gradient tensors): the
        # response cache engages after the warmup, so the timed loops
        # measure the data plane, not per-op negotiation — the same
        # protocol the PR 4 latency bench uses.
        _os.environ["HOROVOD_TRANSPORT"] = transport
        hvd.barrier()
        t0 = _time.perf_counter()
        for i in range(args.transport_iters):
            hvd.allreduce(x, name=f"tb.{transport}", op=hvd.Sum)
        dt = (_time.perf_counter() - t0) / args.transport_iters
        hvd.barrier()
        return dt

    timed("tcp")  # warmup: negotiate both arms' names once
    timed("shm")
    # Fail loudly if the shm arm silently fell back to tcp (no
    # co-located peers, or establishment failed): a ~1.0x "speedup"
    # from tcp-vs-tcp is worse than an error.
    shm_moved = hvd.metrics()["metrics"].get(
        'horovod_transport_bytes_total{direction="sent",transport="shm"}',
        0)
    assert shm_moved > 0, (
        "transport mode measured nothing on shm — are the ranks "
        "co-located and is the shm dir writable?")
    pairs = []
    for r in range(args.transport_rounds):
        if r % 2 == 0:
            a = timed("tcp")
            b = timed("shm")
        else:
            b = timed("shm")
            a = timed("tcp")
        pairs.append((a, b))
    _os.environ["HOROVOD_TRANSPORT"] = "auto"
    ratios = sorted(a / b for a, b in pairs)
    n = hvd.size()
    bus = x.nbytes * 2 * (n - 1) / n
    return {
        "bytes": int(x.nbytes),
        "iters": args.transport_iters,
        "pairs_ms": [[round(a * 1e3, 2), round(b * 1e3, 2)]
                     for a, b in pairs],
        "tcp_ms_median": round(_percentile(
            sorted(a for a, _ in pairs), 0.5) * 1e3, 2),
        "shm_ms_median": round(_percentile(
            sorted(b for _, b in pairs), 0.5) * 1e3, 2),
        "tcp_busbw_GBps": round(bus / _percentile(
            sorted(a for a, _ in pairs), 0.5) / 1e9, 3),
        "shm_busbw_GBps": round(bus / _percentile(
            sorted(b for _, b in pairs), 0.5) / 1e9, 3),
        "ratios": [round(r_, 3) for r_ in ratios],
        "median_speedup": round(_percentile(ratios, 0.5), 3),
    }


def _bench_compression(hvd, np, args):
    """Wire-compression acceptance measurement (docs/running.md "Wire
    compression"): order-alternated paired rounds of the SAME allreduce
    with the codec flipped none<->bf16 between barrier-separated timed
    loops, at 1MB and 16MB. Per-arm steady-state tensor names: the
    codec id is negotiated once per name and replays from the response
    cache (codec choice is cache-replay-stable), so each arm's loops
    measure the data plane under its own codec, not renegotiation.
    Wire bytes are measured from the transport byte counters — exact
    counter accounting, not computed from shapes."""
    import os as _os
    import time as _time

    # Every response in the sweep must be eligible regardless of size.
    _os.environ["HOROVOD_WIRE_COMPRESSION_MIN_BYTES"] = "0"

    def wire_sent(snap):
        return sum(v for k, v in snap.items()
                   if k.startswith("horovod_transport_bytes_total")
                   and 'direction="sent"' in k)

    def timed(mode, x, iters):
        _os.environ["HOROVOD_WIRE_COMPRESSION"] = mode
        hvd.barrier()
        before = wire_sent(hvd.metrics()["metrics"])
        t0 = _time.perf_counter()
        for i in range(iters):
            hvd.allreduce(x, name=f"cb.{mode}.{x.size}", op=hvd.Sum)
        dt = (_time.perf_counter() - t0) / iters
        hvd.barrier()
        sent = (wire_sent(hvd.metrics()["metrics"]) - before) / iters
        return dt, sent

    sizes = [262144, 4194304]  # 1MB / 16MB fp32
    out = []
    for count in sizes:
        x = np.ones(count, np.float32)
        timed("none", x, 2)  # warmup: negotiate both arms' names
        timed("bf16", x, 2)
        saved = hvd.metrics()["metrics"].get(
            'horovod_wire_bytes_saved_total{codec="bf16"}', 0)
        assert saved > 0, (
            "compression mode measured nothing on the bf16 arm — did "
            "the coordinator assign the codec?")
        pairs = []
        for r in range(args.compression_rounds):
            if r % 2 == 0:
                a = timed("none", x, args.compression_iters)
                b = timed("bf16", x, args.compression_iters)
            else:
                b = timed("bf16", x, args.compression_iters)
                a = timed("none", x, args.compression_iters)
            pairs.append((a, b))
        ratios = sorted(a[0] / b[0] for a, b in pairs)
        none_ms = _percentile(sorted(a[0] for a, _ in pairs), 0.5) * 1e3
        bf16_ms = _percentile(sorted(b[0] for _, b in pairs), 0.5) * 1e3
        none_wire = _percentile(sorted(a[1] for a, _ in pairs), 0.5)
        bf16_wire = _percentile(sorted(b[1] for _, b in pairs), 0.5)
        out.append({
            "bytes": int(x.nbytes),
            "pairs_ms": [[round(a[0] * 1e3, 2), round(b[0] * 1e3, 2)]
                         for a, b in pairs],
            "none_ms_median": round(none_ms, 2),
            "bf16_ms_median": round(bf16_ms, 2),
            "none_wire_bytes_per_op": int(none_wire),
            "bf16_wire_bytes_per_op": int(bf16_wire),
            "wire_reduction": round(none_wire / max(bf16_wire, 1), 3),
            "ratios": [round(v, 3) for v in ratios],
            "median_speedup": round(_percentile(ratios, 0.5), 3),
        })
    _os.environ["HOROVOD_WIRE_COMPRESSION"] = "none"
    return {"rows": out,
            "wire_bytes_saved": hvd.metrics()["metrics"].get(
                'horovod_wire_bytes_saved_total{codec="bf16"}', 0)}


def _bench_hier(hvd, np, args):
    """Host-arena acceptance measurement (docs/running.md
    "Transports"): order-alternated paired rounds of the SAME
    leader-mode hierarchical allreduce with the intra-host legs
    flipped per-pair-shm-rings <-> per-host-arena between
    barrier-separated timed loops (HOROVOD_HIER_ARENA is read per
    call; the arena capability bit was agreed at init). Launch over a
    (simulated) multi-host topology:

        HVDRUN_FORCE_LOCAL=1 hvdrun -np 4 -H hostA:2,hostB:2 \\
            python examples/microbench_allreduce.py --mode hier

    Two measurements per round, both order-alternated and paired:

    * ``data_plane`` — the schedule itself, driven directly on the
      backend under a channel scope (hvd.barrier()-synchronized starts,
      back-to-back ops). This is the leg comparison the arena exists
      for: both arms run the identical inter-host ring, only the
      intra-host legs differ.
    * ``engine`` — the same ops through the engine API (enqueue +
      synchronize, steady names so the response cache engages). On a
      box with cores >= ranks the two agree; on an oversubscribed box
      the engine's background negotiation steals CPU from the arena
      ROOT's critical path specifically (the root carries the whole
      fused reduce + inter ring + bcast), so the engine ratio reads
      lower — both are reported."""
    import os as _os
    import time as _time

    from horovod_tpu.backend.base import channel_scope
    from horovod_tpu.backend.ring import hierarchy_valid
    from horovod_tpu.common import basics

    eng = basics.engine()
    backend = eng.backend
    assert hierarchy_valid(backend), (
        "hier mode needs a multi-host topology (simulate one with "
        "-H hostA:2,hostB:2 and HVDRUN_FORCE_LOCAL=1)")
    _os.environ["HOROVOD_RING_THRESHOLD"] = "0"
    _os.environ["HOROVOD_HIERARCHICAL_MODE"] = "leader"
    x = np.ones(args.hier_count, np.float32)

    def timed_direct(arm):
        _os.environ["HOROVOD_HIER_ARENA"] = (
            "auto" if arm == "arena" else "off")
        hvd.barrier()
        t0 = _time.perf_counter()
        with channel_scope(0):
            for _ in range(args.hier_iters):
                backend._hierarchical_allreduce(x, hvd.Sum, owned=False)
        dt = (_time.perf_counter() - t0) / args.hier_iters
        hvd.barrier()
        return dt

    def timed_engine(arm):
        _os.environ["HOROVOD_HIER_ARENA"] = (
            "auto" if arm == "arena" else "off")
        hvd.barrier()
        t0 = _time.perf_counter()
        for i in range(args.hier_iters):
            eng.synchronize(
                eng.enqueue_allreduce(x, name=f"hb.{arm}"), timeout=300)
        dt = (_time.perf_counter() - t0) / args.hier_iters
        hvd.barrier()
        return dt

    for fn in (timed_direct, timed_engine):  # warmup both paths
        fn("rings")
        fn("arena")
    # Fail loudly if the arena arm silently fell back to the per-pair
    # rings (no host arena agreed): a ~1.0x "speedup" from
    # rings-vs-rings is worse than an error.
    arena_ops = hvd.metrics()["metrics"].get(
        "horovod_hier_arena_ops_total", 0)
    assert arena_ops > 0, (
        "hier mode measured nothing on the arena arm — are the hosts' "
        "slots co-located (distinct HOROVOD_HOSTNAME, shm writable)?")
    pairs = {"data_plane": [], "engine": []}
    for r in range(args.hier_rounds):
        for label, fn in (("data_plane", timed_direct),
                          ("engine", timed_engine)):
            if r % 2 == 0:
                a = fn("rings")
                b = fn("arena")
            else:
                b = fn("arena")
                a = fn("rings")
            pairs[label].append((a, b))

    def summarize(ps):
        ratios = sorted(a / b for a, b in ps)
        return {
            "pairs_ms": [[round(a * 1e3, 2), round(b * 1e3, 2)]
                         for a, b in ps],
            "rings_ms_median": round(_percentile(
                sorted(a for a, _ in ps), 0.5) * 1e3, 2),
            "arena_ms_median": round(_percentile(
                sorted(b for _, b in ps), 0.5) * 1e3, 2),
            "ratios": [round(v, 3) for v in ratios],
            "median_speedup": round(_percentile(ratios, 0.5), 3),
        }

    return {
        "bytes": int(x.nbytes),
        "iters": args.hier_iters,
        "data_plane": summarize(pairs["data_plane"]),
        "engine": summarize(pairs["engine"]),
        "median_speedup": summarize(pairs["data_plane"])["median_speedup"],
    }


# The traced-vs-eager benchmark pytree: ~2.36M params (>= the 1M-param
# acceptance shape), transformer-ish layer blocks with biases. ONE
# definition — scripts/perf_report.py imports it so its traced stages
# measure the same shape this microbench and docs/running.md describe.
GRAD_TREE_SHAPES = [(256, 1024), (1024,), (1024, 1024), (1024,),
                    (1024, 512), (512,), (512, 1024), (1024,)]


def _make_grad_tree(np, scale=1.0):
    rng = np.random.RandomState(0)
    return {f"layer{i}": (rng.randn(*s) * scale).astype(np.float32)
            for i, s in enumerate(GRAD_TREE_SHAPES)}


def build_traced_exchange(np, leaves):
    """The traced arm, shared by `--mode traced` and
    scripts/perf_report.py so both published numbers measure the SAME
    harness: a jitted shard_map grouped-psum AVERAGE over a local
    2-device mesh, per-device distinct grads via a stacked leading
    dim. Returns a zero-arg callable running one compiled exchange
    (compile + warmup happen here, outside any timed loop)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.parallel.mesh import create_mesh
    from horovod_tpu.utils.compat import shard_map

    assert len(jax.devices()) >= 2, (
        "the traced arm needs >= 2 local devices — force them with "
        "XLA_FLAGS=--xla_force_host_platform_device_count=2 before "
        "jax's backend is created")
    mesh = create_mesh({"hvd": 2}, devices=jax.devices()[:2])
    stacked = [jnp.asarray(np.stack([v * (d + 1) for d in range(2)]))
               for v in leaves]

    def step(*xs):
        local = [jnp.squeeze(x, 0) for x in xs]
        return tuple(hvd.grouped_allreduce(local, op=hvd.Average))

    compiled = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=tuple(P("hvd") for _ in leaves),
        out_specs=tuple(P() for _ in leaves)))
    jax.block_until_ready(compiled(*stacked))  # compile outside timing
    return lambda: jax.block_until_ready(compiled(*stacked))


def _bench_traced(hvd, np, args):
    """Traced-vs-eager gradient-exchange acceptance measurement
    (docs/running.md "Traced collectives"): order-alternated paired
    rounds of the SAME pytree exchange, once through the eager engine
    (grouped allreduce, steady names, all ranks driving), once through
    the traced/XLA plane (a jitted shard_map grouped psum over rank 0's
    local 2-device mesh — single-controller, so only rank 0 drives it
    while the peers hold at the barrier). Both arms land in ONE JSON.

    Honest caveat (the PR 4/11 precedent): on this loopback container
    the traced arm's "wire" is an XLA all-reduce over two host buffers
    — it measures the DISPATCH cost floor, not the ICI win; and the two
    arms load the box differently (engine: both ranks + negotiation
    threads; traced: rank 0's XLA threads). The dispatch correctness
    (zero engine data-plane bytes — asserted in perf_smoke) is the
    acceptance gate, not this ratio."""
    assert hvd.size() == 2, (
        "traced mode is a PAIRED np=2 comparison (the traced arm is a "
        "2-device local mesh); launch with hvdrun -np 2 — at other "
        "sizes the two arms would do different amounts of work and the "
        "ratio would be meaningless")
    r = hvd.rank()
    tree = _make_grad_tree(np)
    leaves = list(tree.values())
    param_count = sum(int(v.size) for v in leaves)

    def eager_once(i):
        hvd.grouped_allreduce(leaves, name="tr.eager", op=hvd.Average)

    def timed_eager():
        hvd.barrier()
        t0 = time.perf_counter()
        for i in range(args.traced_iters):
            eager_once(i)
        dt = (time.perf_counter() - t0) / args.traced_iters
        hvd.barrier()
        return dt

    # Traced arm: rank 0's local 2-device mesh (devices forced in
    # main() before jax loaded), the shared harness — same world size
    # as the eager arm.
    run_traced = build_traced_exchange(np, leaves) if r == 0 else None

    def timed_traced():
        hvd.barrier()
        dt = 0.0
        if r == 0:
            t0 = time.perf_counter()
            for _ in range(args.traced_iters):
                run_traced()
            dt = (time.perf_counter() - t0) / args.traced_iters
        hvd.barrier()
        return dt

    timed_eager()  # warmup: negotiate the steady name
    timed_traced()
    pairs = []
    for rd in range(args.traced_rounds):
        if rd % 2 == 0:
            a = timed_eager()
            b = timed_traced()
        else:
            b = timed_traced()
            a = timed_eager()
        pairs.append((a, b))
    if r != 0:
        return None
    ratios = sorted(a / b for a, b in pairs)
    return {
        "param_count": param_count,
        "tensors": len(leaves),
        "bytes": int(sum(v.nbytes for v in leaves)),
        "iters": args.traced_iters,
        "pairs_ms": [[round(a * 1e3, 2), round(b * 1e3, 2)]
                     for a, b in pairs],
        "eager_ms_median": round(_percentile(
            sorted(a for a, _ in pairs), 0.5) * 1e3, 2),
        "traced_ms_median": round(_percentile(
            sorted(b for _, b in pairs), 0.5) * 1e3, 2),
        "ratios": [round(v, 3) for v in ratios],
        "median_speedup": round(_percentile(ratios, 0.5), 3),
    }


def _bench_reducescatter(hvd, np, args):
    """hvd.reducescatter timing (the ZeRO gradient leg): steady-state
    names so the engine's response cache engages, the same regime the
    `reducescatter_16mb_ms` perf_report stage gates."""
    count = args.rs_count
    n = hvd.size()
    x = np.ones(count, np.float32) * (hvd.rank() + 1)
    for i in range(args.warmup):
        hvd.reducescatter(x, op=hvd.Sum, name=f"warm.rs.{i}")
    hvd.barrier()
    t0 = time.perf_counter()
    for i in range(args.rs_iters):
        out = hvd.reducescatter(x, op=hvd.Sum, name=f"rs.{i}")
    dt = (time.perf_counter() - t0) / args.rs_iters
    assert out.shape[0] == count // n, out.shape
    # Reduce-scatter moves half an allreduce: (n-1)/n of the buffer
    # per link (the NCCL-tests convention).
    busbw = x.nbytes * (n - 1) / n / dt
    return {"bytes": x.nbytes, "iters": args.rs_iters,
            "lat_us": round(dt * 1e6, 1),
            "busbw_GBps": round(busbw / 1e9, 3)}


def _bench_zero(hvd, np, args):
    """ZeRO acceptance measurement (docs/running.md "ZeRO sharded
    optimizer state"): order-alternated paired rounds of the SAME
    gradient pytree through (a) a replicated update — grouped allreduce
    then a full-tree Adam update on every rank — and (b) the ZeRO path
    — grouped allreduce, owned-shard update, updated-segment allgather
    (`DistributedOptimizer(zero=1)`). Both arms ride the same engine
    grouped collectives with steady names; the delta is the update math
    plus the update allgather. The JSON carries MEASURED per-rank
    optimizer-state bytes for both arms — the (n-1)/n memory claim is
    reported from live buffers, not arithmetic."""
    import jax
    import optax

    n = hvd.size()
    tree = _make_grad_tree(np, scale=1e-2)
    keys = list(tree.keys())
    leaves = list(tree.values())
    params = {k: np.zeros_like(v) for k, v in tree.items()}
    inner = optax.adam(1e-3)

    tx_zero = hvd.DistributedOptimizer(inner, zero=1)
    s_zero = tx_zero.init(params)
    s_rep = inner.init(params)
    state_sharded = int(sum(v.nbytes for v in
                            jax.tree.leaves(s_zero.inner)))
    state_replicated = int(sum(
        np.asarray(v).nbytes for v in jax.tree.leaves(s_rep)))

    def rep_once():
        red = hvd.grouped_allreduce(leaves, name="zero.rep",
                                    op=hvd.Average)
        upd, s = inner.update(dict(zip(keys, red)), rep_box[0], params)
        rep_box[0] = s
        jax.block_until_ready(jax.tree.leaves(upd))

    def zero_once():
        upd, s = tx_zero.update(tree, zero_box[0], params)
        zero_box[0] = s
        jax.block_until_ready(jax.tree.leaves(upd))

    rep_box, zero_box = [s_rep], [s_zero]

    def timed(fn):
        hvd.barrier()
        t0 = time.perf_counter()
        for _ in range(args.zero_iters):
            fn()
        dt = (time.perf_counter() - t0) / args.zero_iters
        hvd.barrier()
        return dt

    timed(rep_once)  # warmup: negotiate the steady names
    timed(zero_once)
    pairs = []
    for rd in range(args.zero_rounds):
        if rd % 2 == 0:
            a = timed(rep_once)
            b = timed(zero_once)
        else:
            b = timed(zero_once)
            a = timed(rep_once)
        pairs.append((a, b))
    if hvd.rank() != 0:
        return None
    return {
        "param_count": int(sum(v.size for v in leaves)),
        "tensors": len(leaves),
        "bytes": int(sum(v.nbytes for v in leaves)),
        "iters": args.zero_iters,
        "state_bytes_replicated": state_replicated,
        "state_bytes_sharded": state_sharded,
        "state_saving": round(state_replicated / state_sharded, 3),
        "pairs_ms": [[round(a * 1e3, 2), round(b * 1e3, 2)]
                     for a, b in pairs],
        "replicated_ms_median": round(_percentile(
            sorted(a for a, _ in pairs), 0.5) * 1e3, 2),
        "zero_ms_median": round(_percentile(
            sorted(b for _, b in pairs), 0.5) * 1e3, 2),
        "zero_overhead": round(_percentile(
            sorted(b / a for a, b in pairs), 0.5), 3),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--sizes", default="16384,262144,4194304",
                   help="comma-separated element counts (float32); the "
                        "default is 64KB / 1MB / 16MB")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--algo",
                   choices=["sweep", "auto", "ring", "segring", "star",
                            "hier"],
                   default="sweep",
                   help="one data-plane algorithm, or 'sweep' (default) "
                        "to compare them all in one run")
    p.add_argument("--segment-bytes", type=int, default=None,
                   help="HOROVOD_RING_SEGMENT_BYTES for the segmented "
                        "ring (default: the library default)")
    p.add_argument("--mode",
                   choices=["bw", "latency", "pipeline", "transport",
                            "compression", "hier", "traced", "zero",
                            "reducescatter"],
                   default="bw",
                   help="bw: the throughput sweep (default); latency: "
                        "small-op p50/p99 enqueue-to-complete, 1-vs-N "
                        "channels; pipeline: mixed-size async window, "
                        "channels=1 vs N paired rounds; transport: "
                        "tcp-vs-shm order-alternated paired rounds of "
                        "the segmented ring on co-located ranks; "
                        "compression: none-vs-bf16 order-alternated "
                        "paired rounds at 1MB/16MB with exact wire-byte "
                        "counter accounting; hier: leader-mode "
                        "hierarchical allreduce with the intra-host "
                        "legs flipped per-pair-rings vs per-host-arena "
                        "(needs a multi-host launch, e.g. simulated "
                        "-H hostA:2,hostB:2 with HVDRUN_FORCE_LOCAL=1); "
                        "traced: eager-engine vs traced-jit gradient "
                        "exchange on the same >=1M-param pytree, "
                        "order-alternated paired rounds (launch with "
                        "hvdrun -np 2); zero: replicated-update vs "
                        "ZeRO reduce/update/allgather on the same "
                        "pytree with measured per-rank state bytes "
                        "(intended np=4); reducescatter: "
                        "hvd.reducescatter timing at --rs-count")
    p.add_argument("--channels", type=int, default=2,
                   help="the N in the 1-vs-N channel comparisons")
    p.add_argument("--lat-count", type=int, default=16384,
                   help="latency-mode element count (default 64KB)")
    p.add_argument("--pipe-rounds", type=int, default=5)
    p.add_argument("--pipe-bigs", type=int, default=2)
    p.add_argument("--pipe-smalls", type=int, default=48)
    p.add_argument("--pipe-big-count", type=int, default=2097152,
                   help="big-op element count (default 8MB)")
    p.add_argument("--pipe-small-count", type=int, default=16384,
                   help="small-op element count (default 64KB)")
    p.add_argument("--transport-count", type=int, default=4194304,
                   help="transport-mode element count (default 16MB)")
    p.add_argument("--transport-iters", type=int, default=5,
                   help="allreduces per timed arm in transport mode")
    p.add_argument("--transport-rounds", type=int, default=5,
                   help="tcp/shm paired rounds in transport mode")
    p.add_argument("--compression-iters", type=int, default=5,
                   help="allreduces per timed arm in compression mode")
    p.add_argument("--compression-rounds", type=int, default=5,
                   help="none/bf16 paired rounds in compression mode")
    p.add_argument("--hier-count", type=int, default=4194304,
                   help="hier-mode element count (default 16MB)")
    p.add_argument("--hier-iters", type=int, default=5,
                   help="allreduces per timed arm in hier mode")
    p.add_argument("--hier-rounds", type=int, default=5,
                   help="rings/arena paired rounds in hier mode")
    p.add_argument("--traced-iters", type=int, default=5,
                   help="exchanges per timed arm in traced mode")
    p.add_argument("--traced-rounds", type=int, default=5,
                   help="eager/traced paired rounds in traced mode")
    p.add_argument("--zero-iters", type=int, default=5,
                   help="updates per timed arm in zero mode")
    p.add_argument("--zero-rounds", type=int, default=5,
                   help="replicated/zero paired rounds in zero mode")
    p.add_argument("--rs-count", type=int, default=4194304,
                   help="reducescatter-mode element count (default "
                        "16MB)")
    p.add_argument("--rs-iters", type=int, default=10,
                   help="reducescatters per timed run")
    args = p.parse_args()

    if args.mode == "traced":
        # The traced arm needs a >= 2-device local mesh on rank 0; the
        # flag must be set before jax's backend is created (lazy, so
        # before the horovod_tpu import below touches jax). An existing
        # count is OVERRIDDEN — a stale =1 exported by an earlier run
        # would silently starve the mesh (the same override semantics
        # as compat.force_host_device_count, inlined because nothing
        # of jax may load before the env is set here).
        import re

        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       "", os.environ.get("XLA_FLAGS", "")).strip()
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()

    if args.mode == "hier":
        # Overlay + arena establishment and the capability agreement
        # happen at init; the timed loops then flip only the intra-host
        # legs. Hard assignment like transport mode — an exported
        # HOROVOD_TRANSPORT=tcp would turn this into rings-vs-rings.
        os.environ["HOROVOD_TRANSPORT"] = "auto"
        os.environ.setdefault("HOROVOD_HIERARCHICAL_ALLREDUCE", "auto")

    if args.mode == "transport":
        # Overlay establishment happens at init; the timed loops then
        # flip only the per-call route. Hard assignment, not
        # setdefault: an exported HOROVOD_TRANSPORT=tcp would
        # otherwise silently turn the measurement into tcp-vs-tcp.
        os.environ["HOROVOD_TRANSPORT"] = "auto"

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.backend.ring import (
        DEFAULT_RING_SEGMENT_BYTES,
        hierarchy_valid,
    )
    from horovod_tpu.common import basics

    seg_bytes = (args.segment_bytes if args.segment_bytes is not None
                 else DEFAULT_RING_SEGMENT_BYTES)

    hvd.init()
    r, n = hvd.rank(), hvd.size()

    if args.mode == "latency":
        rows = _bench_latency(hvd, np, basics, args)
        if r == 0:
            print(f"{'channels':>8} {'bytes':>10} {'p50(us)':>12} "
                  f"{'p99(us)':>12}")
            for row in rows:
                print(f"{row['channels']:>8} {row['bytes']:>10} "
                      f"{row['p50_us']:>12.1f} {row['p99_us']:>12.1f}")
            print(json.dumps({
                "metric": "eager_allreduce_latency", "np": n,
                "event_driven": os.environ.get(
                    "HOROVOD_CYCLE_EVENT_DRIVEN", "1"),
                "rows": [{k: (round(v, 1) if isinstance(v, float) else v)
                          for k, v in row.items()} for row in rows]}))
        return

    if args.mode == "transport":
        summary = _bench_transport(hvd, np, args, seg_bytes)
        if r == 0:
            print(f"transport paired rounds (ms, tcp vs shm): "
                  f"{summary['pairs_ms']}")
            print(f"median speedup shm vs tcp: "
                  f"{summary['median_speedup']}x  "
                  f"(tcp {summary['tcp_busbw_GBps']} GB/s -> "
                  f"shm {summary['shm_busbw_GBps']} GB/s busbw)")
            print(json.dumps(dict(
                {"metric": "eager_allreduce_transport", "np": n},
                **summary)))
        return

    if args.mode == "compression":
        summary = _bench_compression(hvd, np, args)
        if r == 0:
            for row in summary["rows"]:
                print(f"compression {row['bytes']} B: none "
                      f"{row['none_ms_median']}ms vs bf16 "
                      f"{row['bf16_ms_median']}ms "
                      f"({row['median_speedup']}x), wire bytes "
                      f"{row['none_wire_bytes_per_op']} -> "
                      f"{row['bf16_wire_bytes_per_op']} "
                      f"({row['wire_reduction']}x fewer)")
            print(json.dumps(dict(
                {"metric": "eager_allreduce_compression", "np": n},
                **summary)))
        return

    if args.mode == "hier":
        summary = _bench_hier(hvd, np, args)
        if r == 0:
            for label in ("data_plane", "engine"):
                s = summary[label]
                print(f"hier {label} paired rounds (ms, rings vs "
                      f"arena): {s['pairs_ms']}")
                print(f"  median speedup arena legs vs per-pair rings "
                      f"({label}): {s['median_speedup']}x  "
                      f"(rings {s['rings_ms_median']}ms -> "
                      f"arena {s['arena_ms_median']}ms)")
            print(json.dumps(dict(
                {"metric": "eager_allreduce_hier", "np": n}, **summary)))
        return

    if args.mode == "traced":
        summary = _bench_traced(hvd, np, args)
        if r == 0:
            print(f"traced paired rounds (ms, eager-engine vs "
                  f"traced-jit): {summary['pairs_ms']}")
            print(f"median speedup traced vs eager: "
                  f"{summary['median_speedup']}x  "
                  f"(eager {summary['eager_ms_median']}ms -> "
                  f"traced {summary['traced_ms_median']}ms, "
                  f"{summary['param_count']} params / "
                  f"{summary['tensors']} tensors)")
            print(json.dumps(dict(
                {"metric": "allreduce_traced_vs_eager", "np": n},
                **summary)))
        return

    if args.mode == "zero":
        summary = _bench_zero(hvd, np, args)
        if r == 0:
            print(f"zero paired rounds (ms, replicated vs zero): "
                  f"{summary['pairs_ms']}")
            print(f"state bytes/rank: replicated "
                  f"{summary['state_bytes_replicated']} -> sharded "
                  f"{summary['state_bytes_sharded']} "
                  f"({summary['state_saving']}x saving at np={n}); "
                  f"step {summary['replicated_ms_median']}ms -> "
                  f"{summary['zero_ms_median']}ms "
                  f"({summary['zero_overhead']}x)")
            print(json.dumps(dict(
                {"metric": "zero_optimizer", "np": n}, **summary)))
        return

    if args.mode == "reducescatter":
        summary = _bench_reducescatter(hvd, np, args)
        if r == 0:
            print(f"reducescatter {summary['bytes']} B: "
                  f"{summary['lat_us']}us "
                  f"({summary['busbw_GBps']} GB/s busbw)")
            print(json.dumps(dict(
                {"metric": "eager_reducescatter", "np": n}, **summary)))
        return

    if args.mode == "pipeline":
        summary = _bench_pipeline(hvd, np, basics, args)
        if r == 0:
            print(f"pipeline rounds (s): {summary['pairs_s']}")
            print(f"median speedup channels={summary['channels']} vs 1: "
                  f"{summary['median_speedup']}x")
            print(json.dumps(dict(
                {"metric": "eager_allreduce_pipeline", "np": n},
                **summary)))
        return

    backend = basics.engine().backend if basics.engine() else None

    if args.algo in ("sweep",):
        algos = ["star", "ring", "segring"]
        hier_ok = backend is not None and hierarchy_valid(backend)
        if hier_ok:
            algos.append("hier")
    else:
        algos = [args.algo]
        hier_ok = backend is not None and hierarchy_valid(backend)

    rows, skipped = [], []
    for algo in algos:
        if algo == "hier":
            if not hier_ok:
                skipped.append({"algo": "hier",
                                "reason": "topology not hierarchical "
                                          "(needs local_size>1 and "
                                          "cross_size>1)"})
                continue
            # The hierarchical toggle is normally set at init from
            # HOROVOD_HIERARCHICAL_ALLREDUCE / autotune; for the sweep
            # every rank flips it at the same schedule point, which is
            # exactly the collective-consistency the gate needs.
            backend.hierarchical = True
        elif backend is not None and algo != "auto":
            # 'auto' measures the as-launched config untouched.
            backend.hierarchical = False
        _set_algo_env(algo, seg_bytes)
        for count in [int(s) for s in args.sizes.split(",")]:
            rows.append(_bench_one(hvd, np, algo, count,
                                   args.iters, args.warmup))

    if r == 0:
        print(f"{'algo':>8} {'bytes':>12} {'latency(us)':>14} "
              f"{'busbw(GB/s)':>12}")
        for row in rows:
            print(f"{row['algo']:>8} {row['bytes']:>12} "
                  f"{row['lat_us']:>14.1f} {row['busbw_GBps']:>12.3f}")
        for s in skipped:
            print(f"{s['algo']:>8} skipped: {s['reason']}")
        print(json.dumps({
            "metric": "eager_allreduce",
            "np": n,
            "algo": args.algo,
            "segment_bytes": seg_bytes,
            "rows": [{k: (round(v, 3) if isinstance(v, float) else v)
                      for k, v in row.items()} for row in rows],
            "skipped": skipped,
        }))


if __name__ == "__main__":
    main()
