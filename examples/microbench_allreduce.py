"""Eager-engine allreduce micro-benchmark: latency / bandwidth vs size.

Measures the process-mode data plane the way the reference community
benchmarks Gloo vs MPI backends — per-op latency for small tensors and
achieved bus bandwidth for large ones, for both the ring and star
algorithms (ref methodology: gloo ring allreduce,
horovod/common/ops/gloo_operations.cc:119-166).

Run under the launcher (2-8 processes):

    hvdrun -np 2 python examples/microbench_allreduce.py
    hvdrun -np 4 python examples/microbench_allreduce.py --algo star

Rank 0 prints a table and one JSON summary line.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import argparse
import json
import os
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--sizes", default="1024,16384,262144,4194304",
                   help="comma-separated element counts (float32)")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--algo", choices=["ring", "star"], default=None,
                   help="force the data-plane algorithm (default: auto)")
    args = p.parse_args()

    if args.algo == "star":
        os.environ["HOROVOD_CPU_OPERATIONS"] = "star"
    elif args.algo == "ring":
        os.environ["HOROVOD_RING_THRESHOLD"] = "0"

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r, n = hvd.rank(), hvd.size()
    rows = []
    for count in [int(s) for s in args.sizes.split(",")]:
        x = np.ones(count, np.float32)
        for i in range(args.warmup):
            hvd.allreduce(x, name=f"warm.{count}.{i}")
        hvd.barrier()
        t0 = time.perf_counter()
        for i in range(args.iters):
            hvd.allreduce(x, name=f"bench.{count}.{i}")
        dt = (time.perf_counter() - t0) / args.iters
        # Bus bandwidth uses the ring-allreduce wire factor 2(n-1)/n
        # (bytes each rank moves per link), the NCCL-tests convention.
        busbw = x.nbytes * 2 * (n - 1) / n / dt
        rows.append({"bytes": x.nbytes, "lat_us": dt * 1e6,
                     "busbw_MBps": busbw / 1e6})
    if r == 0:
        print(f"{'bytes':>12} {'latency(us)':>14} {'busbw(MB/s)':>14}")
        for row in rows:
            print(f"{row['bytes']:>12} {row['lat_us']:>14.1f} "
                  f"{row['busbw_MBps']:>14.1f}")
        print(json.dumps({
            "metric": "eager_allreduce",
            "np": n,
            "algo": args.algo or "auto",
            "rows": [{k: round(v, 1) for k, v in row.items()}
                     for row in rows],
        }))


if __name__ == "__main__":
    main()
