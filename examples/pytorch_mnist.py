"""PyTorch MNIST with horovod_tpu.torch — the reference's canonical
first script (ref: examples/pytorch_mnist.py) on the TPU build's torch
adapter. Synthetic data keeps it runnable offline.

Run:  hvdrun -np 2 python examples/pytorch_mnist.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x.view(x.size(0), -1))))


def main():
    hvd.init()
    torch.manual_seed(42 + hvd.rank())

    model = Net()
    optimizer = torch.optim.SGD(model.parameters(), lr=0.01 * hvd.size(),
                                momentum=0.9)
    # Wrap: gradients allreduce across ranks each step
    # (ref: horovod/torch/optimizer.py:32 _DistributedOptimizer).
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters()
    )
    # Rank 0's initial weights everywhere
    # (ref: torch/functions.py:30 broadcast_parameters).
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    rng = np.random.RandomState(hvd.rank())
    for step in range(30):
        x = torch.from_numpy(rng.rand(32, 784).astype(np.float32))
        y = torch.from_numpy(rng.randint(0, 10, 32))
        optimizer.zero_grad()
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        optimizer.step()
        if step % 10 == 0 and hvd.rank() == 0:
            print(f"step {step} loss {loss.item():.4f}")

    # Metric averaging across ranks (ref: MetricAverageCallback).
    final = hvd.allreduce(torch.tensor([loss.item()]), name="final_loss")
    if hvd.rank() == 0:
        print(f"mean final loss across {hvd.size()} ranks: "
              f"{final.item():.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
