"""Adasum delta-model training with PyTorch.

`DistributedOptimizer(op=hvd.Adasum)` applies the LOCAL optimizer step
and Adasum-combines the weight deltas (VHDD) — the reference's
delta-model optimizer, not a gradient allreduce (ref:
horovod/torch/optimizer.py:210-321, dispatch :437-445; docs/adasum.md).
No lr rescaling with world size is needed.

Run:  hvdrun -np 2 python examples/pytorch_adasum_delta.py
(power-of-2 world sizes only — the VHDD ladder requires it)
"""
import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd


def main():
    hvd.init()
    torch.manual_seed(0)

    model = torch.nn.Sequential(
        torch.nn.Linear(8, 32), torch.nn.ReLU(), torch.nn.Linear(32, 1)
    )
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    # Note: NO lr * hvd.size() scaling — Adasum is scale-insensitive.
    opt = hvd.DistributedOptimizer(
        torch.optim.Adam(model.parameters(), lr=1e-2),
        named_parameters=model.named_parameters(),
        op=hvd.Adasum,
    )

    rng = np.random.RandomState(hvd.rank())
    X = torch.from_numpy(rng.randn(256, 8).astype(np.float32))
    W = torch.from_numpy(np.linspace(-1, 1, 8).astype(np.float32))
    Y = (X @ W).unsqueeze(-1)

    for epoch in range(20):
        opt.zero_grad()
        loss = F.mse_loss(model(X), Y)
        loss.backward()
        opt.step()  # local Adam step + VHDD delta combine
        if hvd.rank() == 0 and epoch % 5 == 0:
            print(f"epoch {epoch} loss {loss.item():.4f}")

    # Every rank holds the identical combined model.
    flat = torch.cat([p.detach().reshape(-1) for p in model.parameters()])
    gathered = hvd.allgather(flat[None, :])
    assert torch.allclose(gathered[0], gathered[-1], atol=1e-6)
    if hvd.rank() == 0:
        print("ranks agree; final loss", float(loss))


if __name__ == "__main__":
    main()
