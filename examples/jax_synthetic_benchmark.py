"""Synthetic benchmark — the reference's headline measurement tool.

(ref: examples/pytorch_synthetic_benchmark.py — same CLI shape, prints
`Img/sec per chip` and `Total img/sec on N chip(s)`.) The step is one
jitted SPMD program over the dp mesh: XLA fuses the gradient psums into
the backward pass on ICI.

    python examples/jax_synthetic_benchmark.py --model resnet50
    python examples/jax_synthetic_benchmark.py --model gpt2-small --batch-size 8
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import argparse
import time

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-chip batch size")
    p.add_argument("--num-warmup-batches", type=int, default=3)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=3)
    p.add_argument("--image-size", type=int, default=224)
    args = p.parse_args()

    import jax
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models import get_model
    from horovod_tpu.parallel.mesh import create_mesh
    from horovod_tpu.parallel.train import (
        lm_loss,
        make_train_step,
        softmax_xent,
    )

    hvd.init()
    n = len(jax.devices())
    mesh = create_mesh({"dp": n})
    spec = get_model(args.model)
    model = spec.make_model()

    global_batch = args.batch_size * n
    batch = spec.make_batch(global_batch)
    is_image = spec.kind == "image"
    rng = np.random.RandomState(0)
    if is_image:
        labels = rng.randint(0, 1000, (global_batch,), dtype=np.int32)
        batch = (batch[0], labels)
        loss_fn = softmax_xent
        has_bn = args.model.startswith("resnet")
    else:
        loss_fn = lm_loss
        has_bn = False

    build = make_train_step(
        model, optax.sgd(0.01, momentum=0.9), loss_fn, mesh=mesh,
        has_batch_stats=has_bn,
    )
    init_fn, step_fn, _ = build(jax.random.PRNGKey(0), *batch)
    state = init_fn(jax.random.PRNGKey(0))

    from jax.sharding import NamedSharding, PartitionSpec as P

    batch = tuple(
        jax.device_put(b, NamedSharding(mesh, P("dp"))) for b in batch
    )

    def run_batches(state, k):
        for _ in range(k):
            state, loss = step_fn(state, *batch)
        jax.device_get(loss)
        return state

    state = run_batches(state, args.num_warmup_batches)

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        state = run_batches(state, args.num_batches_per_iter)
        dt = time.perf_counter() - t0
        ips = global_batch * args.num_batches_per_iter / dt
        img_secs.append(ips / n)
        print(f"Iter #{i}: {ips:.1f} img/sec total")

    mean, std = np.mean(img_secs), 1.96 * np.std(img_secs)
    print(f"Img/sec per chip: {mean:.1f} +-{std:.1f}")
    print(f"Total img/sec on {n} chip(s): {mean * n:.1f} +-{std * n:.1f}")


if __name__ == "__main__":
    main()
