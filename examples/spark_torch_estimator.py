"""Torch estimator + Store walkthrough (ref: the reference's
horovod/spark/torch/estimator.py usage): fit a torch.nn.Module on a
DataFrame data-parallel with a streaming shard reader and per-epoch
checkpoints, then resume and transform.

Runs with plain pandas (no Spark needed); pass a pyspark DataFrame the
same way when running inside a Spark session.

Run:  python examples/spark_torch_estimator.py [--num-proc 2]
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np
import pandas as pd
import torch

from horovod_tpu.spark import TorchEstimator
from horovod_tpu.spark.store import Store


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num-proc", type=int, default=None)
    p.add_argument("--epochs", type=int, default=8)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    x = rng.rand(4096).astype(np.float32)
    df = pd.DataFrame({"x": x, "y": 3.0 * x + 1.0})

    with tempfile.TemporaryDirectory() as d:
        store = Store.create(d)
        net = torch.nn.Linear(1, 1)
        est = TorchEstimator(
            model=net,
            optimizer=torch.optim.SGD(net.parameters(), lr=0.5),
            loss=lambda out, y: torch.nn.functional.mse_loss(
                out.squeeze(-1), y),
            feature_cols=["x"], label_col="y",
            epochs=args.epochs, batch_size=64,
            store=store, run_id="example",
            num_proc=args.num_proc,
        )
        model = est.fit(df)
        pred = model.transform(df)
        err = np.abs(np.stack(pred["prediction"].to_numpy()).ravel()
                     - df["y"].to_numpy()).mean()
        print(f"mean abs error after {args.epochs} epochs: {err:.4f}")
        ck = store.load_checkpoint("example")
        print(f"last store checkpoint epoch: {ck['epoch']}")


if __name__ == "__main__":
    main()
