"""Elastic MNIST — fault-tolerant training with dynamic hosts.

(ref: examples/elastic/pytorch_mnist_elastic.py.) Run with a discovery
script that prints the current `host[:slots]` set:

    hvdrun -np 2 --min-np 1 --max-np 4 \
        --host-discovery-script ./discover_hosts.sh \
        python examples/jax_mnist_elastic.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import argparse

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.001)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.elastic.state import JaxState
    from horovod_tpu.models import MnistCNN

    hvd.init()

    from jax_mnist import synthetic_mnist

    x, y = synthetic_mnist()
    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), x[: args.batch_size])
    tx = hvd.DistributedOptimizer(optax.adam(args.lr * hvd.size()))

    @jax.jit
    def grad_step(params, bx, by):
        def loss_fn(p):
            logits = model.apply(p, bx)
            onehot = jax.nn.one_hot(by, 10)
            return -jnp.mean(
                jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1)
            )

        return jax.value_and_grad(loss_fn)(params)

    state = JaxState(
        params=params, opt_state=tx.init(params), epoch=0, batch=0
    )

    @hvd.elastic.run
    def train(state):
        steps = len(x) // args.batch_size
        while state.epoch < args.epochs:
            # Re-shard data for the *current* world each epoch.
            xs = x[hvd.rank()::hvd.size()]
            ys = y[hvd.rank()::hvd.size()]
            while state.batch < steps // hvd.size():
                lo = state.batch * args.batch_size
                bx = xs[lo:lo + args.batch_size]
                by = ys[lo:lo + args.batch_size]
                if len(bx) == 0:
                    break
                loss, grads = grad_step(state.params, bx, by)
                upd, state.opt_state = tx.update(
                    grads, state.opt_state, state.params
                )
                import optax as _optax

                state.params = _optax.apply_updates(state.params, upd)
                state.batch += 1
                if state.batch % 10 == 0:
                    state.commit()
            if hvd.rank() == 0:
                print(f"epoch {state.epoch}: loss={float(loss):.4f} "
                      f"(world size {hvd.size()})")
            state.epoch += 1
            state.batch = 0
            state.commit()

    train(state)


if __name__ == "__main__":
    main()
