"""MNIST training — the framework's first-run example.

TPU-native port of the reference's first-run examples
(ref: examples/tensorflow2_mnist.py, examples/pytorch_mnist.py). Run:

    python examples/jax_mnist.py                 # mesh mode, all chips
    hvdrun -np 2 python examples/jax_mnist.py    # process mode, 2 ranks

Uses a synthetic MNIST-shaped dataset by default (no network egress);
pass --data-dir with the standard IDX files to train on real MNIST.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import argparse
import gzip
import os
import struct

import numpy as np


def load_mnist(data_dir):
    """Standard IDX files (train-images-idx3-ubyte.gz etc.)."""
    def read_idx(path):
        with gzip.open(path, "rb") as f:
            magic, = struct.unpack(">I", f.read(4))
            ndim = magic & 0xFF
            dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
            return np.frombuffer(f.read(), np.uint8).reshape(dims)

    x = read_idx(os.path.join(data_dir, "train-images-idx3-ubyte.gz"))
    y = read_idx(os.path.join(data_dir, "train-labels-idx1-ubyte.gz"))
    return x.astype(np.float32) / 255.0, y.astype(np.int32)


def synthetic_mnist(n=8192, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, n).astype(np.int32)
    # Make it learnable: brighten a quadrant per class.
    for i in range(n):
        q = y[i] % 4
        r, c = divmod(q, 2)
        x[i, r * 14:(r + 1) * 14, c * 14:(c + 1) * 14] += y[i] / 10.0
    return x, y


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data-dir", default=None)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.001)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models import MnistCNN

    hvd.init()

    x, y = (load_mnist(args.data_dir) if args.data_dir
            else synthetic_mnist())
    # Shard the dataset across ranks the way the reference's
    # DistributedSampler does (examples/pytorch_mnist.py).
    n_shards = hvd.size() if hvd.mode() == "process" else 1
    shard = hvd.rank() if hvd.mode() == "process" else 0
    x, y = x[shard::n_shards], y[shard::n_shards]

    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), x[: args.batch_size])

    # Scale LR by world size (linear-scaling rule the reference
    # documents, README.rst:91).
    tx = hvd.DistributedOptimizer(optax.adam(args.lr * hvd.size()))
    opt_state = tx.init(params)

    # Start ranks from identical weights (ref: broadcast_parameters,
    # horovod/torch/functions.py:30).
    params = hvd.broadcast_parameters(params, root_rank=0)

    # Compute grads under jit; run the (allreducing) optimizer update
    # eagerly so the same script serves mesh mode AND process mode —
    # exactly how the reference's torch script computes grads on device
    # and lets hooks allreduce them (examples/pytorch_mnist.py). For the
    # fully-jitted SPMD path see jax_synthetic_benchmark.py / wrap_step.
    @jax.jit
    def grad_step(params, bx, by):
        def loss_fn(p):
            logits = model.apply(p, bx)
            onehot = jax.nn.one_hot(by, 10)
            return -jnp.mean(
                jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1)
            )

        return jax.value_and_grad(loss_fn)(params)

    steps_per_epoch = len(x) // args.batch_size
    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(len(x))
        for i in range(steps_per_epoch):
            idx = perm[i * args.batch_size:(i + 1) * args.batch_size]
            loss, grads = grad_step(params, x[idx], y[idx])
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={float(loss):.4f}")

    if hvd.rank() == 0:
        logits = model.apply(params, x[:1024])
        acc = float(np.mean(np.argmax(logits, -1) == y[:1024]))
        print(f"train accuracy (first 1024): {acc:.3f}")


if __name__ == "__main__":
    main()
