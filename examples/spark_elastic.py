"""Mid-job elastic training on Spark (ref: horovod/spark/runner.py:303).

`run_elastic` launches max_np Spark tasks as a task-service fleet; the
in-driver elastic driver spawns/kills workers through them, so the job
starts as soon as min_np tasks are live, shrinks when a task dies, and
grows when one (re)appears — with `hvd.elastic` state carrying training
through every reset. See docs/spark.md.

Run inside a PySpark session:

    python examples/spark_elastic.py
"""
import numpy as np


def train():
    import horovod_tpu as hvd
    from horovod_tpu.elastic.state import JaxState

    hvd.init()
    state = JaxState(params=np.zeros(4, np.float32), batch=0)

    X = np.arange(32.0, dtype=np.float32).reshape(8, 4) / 32.0
    Y = X @ np.array([1.0, 2.0, -1.0, 0.5], np.float32)

    @hvd.elastic.run
    def loop(state):
        while state.batch < 200:
            # toy gradient step; real jobs jit this (see
            # tests/test_elastic_integration.py GSPMD worker)
            g = 2 * X.T @ (X @ state.params - Y) / len(Y)
            g = hvd.allreduce(g, name="g")
            state.params = state.params - 0.3 * np.asarray(g)
            state.batch += 1
            state.commit()
        return state.params

    params = loop(state)
    return hvd.rank(), params.tolist()


def main():
    import horovod_tpu.spark as hvd_spark

    results = hvd_spark.run_elastic(train, num_proc=2, min_np=1, max_np=4)
    print("per-rank results:", results)


if __name__ == "__main__":
    main()
