"""TF2 MNIST with horovod_tpu.tensorflow — the reference's first-run
TF2 example ported to this framework
(ref: examples/tensorflow2_mnist.py: DistributedGradientTape + variable
broadcast on first batch + rank-sharded data + lr scaling).

Run:
    python examples/tensorflow2_mnist.py               # single process
    hvdrun -np 2 python examples/tensorflow2_mnist.py  # 2 ranks

Uses a synthetic MNIST-shaped dataset by default (no network egress);
pass --data-dir with the standard IDX files for real MNIST.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from jax_mnist import load_mnist, synthetic_mnist  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data-dir", default=None)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.001)
    args = p.parse_args()

    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
    import numpy as np
    import tensorflow as tf
    import keras

    import horovod_tpu.tensorflow as hvd

    hvd.init()

    x, y = (load_mnist(args.data_dir) if args.data_dir
            else synthetic_mnist())
    # Shard the dataset across ranks (ref: tensorflow2_mnist.py shard).
    x, y = x[hvd.rank()::hvd.size()], y[hvd.rank()::hvd.size()]
    dataset = (
        tf.data.Dataset.from_tensor_slices(
            (x[..., None].astype("float32"), y.astype("int64"))
        )
        .shuffle(4096, seed=hvd.rank())
        .batch(args.batch_size)
    )

    model = keras.Sequential([
        keras.Input((28, 28, 1)),
        keras.layers.Conv2D(32, 3, activation="relu"),
        keras.layers.MaxPooling2D(),
        keras.layers.Conv2D(64, 3, activation="relu"),
        keras.layers.MaxPooling2D(),
        keras.layers.Flatten(),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10),
    ])
    loss_obj = keras.losses.SparseCategoricalCrossentropy(from_logits=True)
    # Scale LR by world size (ref: tensorflow2_mnist.py `0.001 * hvd.size()`).
    opt = keras.optimizers.Adam(args.lr * hvd.size())

    def training_step(images, labels, first_batch):
        with tf.GradientTape() as tape:
            logits = model(images, training=True)
            loss_value = loss_obj(labels, logits)
        tape = hvd.DistributedGradientTape(tape)
        grads = tape.gradient(loss_value, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if first_batch:
            # Broadcast initial state once variables exist
            # (ref: tensorflow2_mnist.py first_batch broadcast note).
            hvd.broadcast_variables(model.variables, root_rank=0)
            opt_vars = opt.variables
            hvd.broadcast_variables(
                list(opt_vars() if callable(opt_vars) else opt_vars),
                root_rank=0,
            )
        return loss_value

    step = 0
    for epoch in range(args.epochs):
        for images, labels in dataset:
            loss_value = training_step(images, labels, step == 0)
            step += 1
            if step % 50 == 0 and hvd.rank() == 0:
                print(f"step {step}: loss={float(loss_value):.4f}")

    if hvd.rank() == 0:
        logits = model(x[:1024, ..., None].astype("float32"))
        acc = float(np.mean(np.argmax(logits.numpy(), -1) == y[:1024]))
        print(f"train accuracy (first 1024): {acc:.3f}")


if __name__ == "__main__":
    main()
