"""MXNet gluon MNIST with horovod_tpu.mxnet (ref: the reference's
examples/mxnet_mnist.py). Requires mxnet installed; synthetic data
keeps it runnable offline.

Run:  hvdrun -np 2 python examples/mxnet_mnist.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np

import mxnet as mx
from mxnet import autograd, gluon

import horovod_tpu.mxnet as hvd


def main():
    hvd.init()
    mx.random.seed(42 + hvd.rank())

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(128, activation="relu"), gluon.nn.Dense(10))
    net.initialize()

    # Rank 0's initial weights everywhere; trainer allreduces grads
    # (ref: horovod/mxnet/__init__.py:91 DistributedTrainer).
    params = net.collect_params()
    trainer = hvd.DistributedTrainer(params, "sgd",
                                     {"learning_rate": 0.01 * hvd.size()})
    hvd.broadcast_parameters(params, root_rank=0)

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(hvd.rank())
    for step in range(30):
        x = mx.nd.array(rng.rand(32, 784).astype(np.float32))
        y = mx.nd.array(rng.randint(0, 10, 32))
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(32)
        if step % 10 == 0 and hvd.rank() == 0:
            print(f"step {step} loss {float(loss.asscalar()):.4f}")

    final = hvd.allreduce(mx.nd.array([loss.asscalar()]), name="final")
    if hvd.rank() == 0:
        print(f"mean final loss across {hvd.size()} ranks: "
              f"{float(final.asscalar()):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
