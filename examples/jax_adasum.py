"""Adasum gradient aggregation demo (ref: examples/adasum_small_model.py
and the GPT-2+Adasum north-star config in BASELINE.json).

Adasum combines gradients scaling-insensitively: for orthogonal
gradients it sums, for parallel ones it averages — so the effective LR
doesn't need the 1/N rescale of plain averaging (ref:
horovod/common/ops/adasum/adasum.h). Two spellings:

  * traced: `hvd.allreduce(g, op=hvd.Adasum)` inside shard_map lowers to
    the ppermute ladder in ops/adasum.py;
  * eager (process mode): the engine routes ADASUM requests through the
    native C++ VHDD kernel (horovod_tpu/cc/core.cc).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def main():
    import functools

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.utils.compat import shard_map

    hvd.init()

    if hvd.mode() == "process":
        # Eager path through the engine (power-of-2 world required).
        g = np.ones(8, np.float32) * (hvd.rank() + 1)
        out = hvd.allreduce(g, op=hvd.Adasum, name="grad")
        print(f"rank {hvd.rank()}: adasum -> {out[:3]}")
        return

    # Mesh mode: Adasum inside one SPMD step.
    mesh = hvd.mesh()
    axis = hvd.axis_name()
    n = mesh.size

    def per_chip_step(w, x, y):
        def loss(w):
            return jnp.mean((x @ w - y) ** 2)

        l, g = jax.value_and_grad(loss)(w)
        g = hvd.allreduce(g, op=hvd.Adasum, axis_name=axis)
        return w - 0.1 * g, hvd.allreduce(l, axis_name=axis)

    rng = np.random.RandomState(0)
    W = jnp.zeros((4, 1))
    X = rng.randn(8 * n, 4).astype(np.float32)
    Y = (X @ rng.randn(4, 1)).astype(np.float32)

    step = jax.jit(shard_map(
        per_chip_step, mesh=mesh,
        in_specs=(P(), P(axis), P(axis)),
        out_specs=(P(), P()),
    ))
    for i in range(20):
        W, loss = step(W, X, Y)
    print(f"adasum-trained loss after 20 steps: {float(loss):.6f}")


if __name__ == "__main__":
    main()
