"""Estimator + Store walkthrough (ref: the reference's Spark Keras
estimator examples, horovod/spark/keras/estimator.py usage): materialize
a DataFrame to store Parquet, fit data-parallel with per-epoch
checkpoints, resume, and transform.

Runs with plain pandas (no Spark needed); pass a pyspark DataFrame the
same way when running inside a Spark session.

Run:  python examples/spark_estimator.py [--num-proc 2]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
import tempfile

import numpy as np
import pandas as pd

import flax.linen as nn
import jax.numpy as jnp
import optax

from horovod_tpu.spark.estimator import JaxEstimator
from horovod_tpu.spark.store import Store


class Regressor(nn.Module):
    @nn.compact
    def __call__(self, x):
        h = nn.relu(nn.Dense(16)(x))
        return nn.Dense(1)(h).squeeze(-1)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num-proc", type=int, default=1)
    p.add_argument("--store", default=None,
                   help="store prefix path (default: a temp dir)")
    args = p.parse_args()

    rng = np.random.RandomState(0)
    x1, x2 = rng.rand(512), rng.rand(512)
    df = pd.DataFrame({
        "x1": x1.astype(np.float32),
        "x2": x2.astype(np.float32),
        "y": (3.0 * x1 - 2.0 * x2 + 0.5).astype(np.float32),
    })

    store = Store.create(args.store or tempfile.mkdtemp(prefix="hvd-store-"))
    est = JaxEstimator(
        model=Regressor(),
        optimizer=optax.adam(1e-2),
        loss=lambda pred, y: jnp.mean((pred - y) ** 2),
        feature_cols=["x1", "x2"],
        label_col="y",
        epochs=10,
        batch_size=64,
        num_proc=args.num_proc,
        store=store,
        run_id="example",
    )
    model = est.fit(df)

    ck = store.load_checkpoint("example")
    print(f"checkpointed epoch: {ck['epoch']} "
          f"(store: {store.prefix_path})")

    pred = model.transform(df.head(5))
    print(pred[["y", "prediction"]])

    # A second fit with more epochs resumes from the checkpoint instead
    # of restarting (same data fingerprint + run_id).
    est.epochs = 14
    est.fit(df)
    print(f"resumed to epoch: {store.load_checkpoint('example')['epoch']}")


if __name__ == "__main__":
    main()
