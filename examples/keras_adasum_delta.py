"""Adasum delta-model training with Keras under a TRACED model.fit.

`DistributedOptimizer(op=hvd.Adasum)` applies the LOCAL optimizer step
and Adasum-combines the weight deltas (VHDD) — the reference's
delta-model optimizer, not a gradient allreduce (ref:
horovod/tensorflow/__init__.py:334-428; docs/adasum.md). With
`backward_passes_per_step=k` the combine fires every k-th batch, and
the schedule is IN-GRAPH (`_is_comm_step` pattern), so it survives a
compiled `model.fit` — no `run_eagerly=True` needed. No lr rescaling
with world size is needed: Adasum is scale-insensitive.

Run:  hvdrun -np 2 python examples/keras_adasum_delta.py
(power-of-2 world sizes only — the VHDD ladder requires it)
"""
import keras
import numpy as np

import horovod_tpu.keras as hvd


def main():
    hvd.init()
    keras.utils.set_random_seed(0)

    model = keras.Sequential([
        keras.Input((8,)),
        keras.layers.Dense(32, activation="relu"),
        keras.layers.Dense(1),
    ])
    # Local Adam steps every batch; delta-combine every 2nd batch.
    opt = hvd.DistributedOptimizer(
        keras.optimizers.Adam(1e-2), op=hvd.Adasum,
        backward_passes_per_step=2,
    )
    model.compile(optimizer=opt, loss="mse")  # traced train_step

    rng = np.random.RandomState(hvd.rank())  # rank-local data
    X = rng.randn(256, 8).astype(np.float32)
    W = np.linspace(-1, 1, 8).astype(np.float32)
    Y = (X @ W)[:, None]

    # Broadcast BEFORE the first step, not via the batch-0 callback:
    # the Adasum wrapper captures its delta baseline (start = var) at
    # the FIRST apply(), so ranks must already hold identical weights
    # there — a post-batch broadcast would leave each rank's baseline
    # at its own pre-broadcast values and the combines would diverge
    # (docs/adasum.md).
    hvd.broadcast_variables(model.variables, root_rank=0)
    hist = model.fit(X, Y, epochs=10, batch_size=64, verbose=0)
    if hvd.rank() == 0:
        losses = hist.history["loss"]
        print(f"loss {losses[0]:.4f} -> {losses[-1]:.4f} "
              f"over {len(losses)} epochs on {hvd.size()} ranks")


if __name__ == "__main__":
    main()
