"""GPT-2 training across every parallelism axis the framework offers.

The reference's north-star LM config is "GPT-2 1.3B + Adasum"
(BASELINE.json); this script trains any registry GPT-2 size over a
configurable pp x dp x ep x sp x tp mesh with ring/Ulysses attention and
optional MoE — capabilities beyond the reference's DP-only scope
(SURVEY.md §2.6).

    python examples/jax_gpt2_train.py --model gpt2-small --dp 4 --tp 2
    python examples/jax_gpt2_train.py --model gpt2-1p3b --dp 8 --tp 4 \
        --sp 2 --attn ring --remat
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import argparse
import time

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt2-small")
    p.add_argument("--batch-size", type=int, default=8, help="global batch")
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--ep", type=int, default=1)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--attn", default="dense",
                   choices=["dense", "ring", "ulysses", "flash"])
    p.add_argument("--n-experts", type=int, default=0)
    p.add_argument("--remat", action="store_true")
    args = p.parse_args()

    import dataclasses

    import jax
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models.pipelined import PipelinedLM
    from horovod_tpu.models.transformer import GPT2_CONFIGS, TransformerLM
    from horovod_tpu.parallel.mesh import create_mesh
    from horovod_tpu.parallel.sharding import DEFAULT_RULES, PIPELINE_RULES
    from horovod_tpu.parallel.train import lm_loss, make_train_step

    hvd.init()
    axes = {k: v for k, v in
            [("pp", args.pp), ("dp", args.dp), ("ep", args.ep),
             ("sp", args.sp), ("tp", args.tp)]}
    mesh = create_mesh(axes)

    cfg = GPT2_CONFIGS[args.model]
    cfg = dataclasses.replace(
        cfg,
        max_len=max(cfg.max_len, args.seq_len),
        attn_impl=args.attn,
        remat=args.remat,
        n_experts=args.n_experts,
        scan_layers=args.pp > 1,
        # Training path: bf16 logits (the measured config — lm_loss
        # upcasts to f32 inside its softmax, so only the lm-head HBM
        # traffic changes; the library default stays f32, ADVICE r14).
        logits_dtype=jax.numpy.bfloat16,
    )
    if args.pp > 1:
        model = PipelinedLM(cfg, mesh)
        rules = PIPELINE_RULES
    else:
        model = TransformerLM(cfg)
        rules = DEFAULT_RULES

    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (args.batch_size, args.seq_len), dtype=np.int32
    )

    tx = optax.adamw(args.lr)
    build = make_train_step(
        model, tx, lm_loss, mesh=mesh, rules=rules, shard_seq=args.sp > 1,
        moe_aux_weight=0.01 if args.n_experts else 0.0,
    )
    init_fn, step_fn, _ = build(jax.random.PRNGKey(0), ids)
    state = init_fn(jax.random.PRNGKey(0))

    for i in range(args.steps):
        t0 = time.perf_counter()
        state, loss = step_fn(state, ids)
        loss = float(loss)
        if hvd.rank() == 0:
            dt = time.perf_counter() - t0
            toks = args.batch_size * args.seq_len / dt
            print(f"step {i}: loss={loss:.4f}  {toks:,.0f} tokens/sec")


if __name__ == "__main__":
    main()
