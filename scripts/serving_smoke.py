#!/usr/bin/env python
"""Serving-plane smoke: the ROADMAP item-4 acceptance scenario, end to
end on one box (docs/serving.md).

A real 4-rank mesh serves HTTP inference through the rank-0 front door
while this parent process plays N concurrent clients. Three phases, one
continuous job:

1. **Baseline** — concurrent clients, measured p50/p99 request latency
   asserted finite and sane, every request 200.
2. **Weight refresh mid-traffic** — the parent publishes a new weight
   version into the watched checkpoint dir (the durability-plane
   layout); replicas background-load and hot-swap between batches.
   ZERO dropped requests across the swap, and post-swap responses
   provably reflect the new weights (the output value and the
   `weight_step` echo both flip).
3. **Wedge one replica** — a non-zero rank freezes (process alive,
   sockets open, heartbeats stop) under UNBOUNDED socket timeouts; the
   liveness plane declares it dead, the serving plane evicts it and
   re-meshes the survivors, and every request accepted during the
   outage still completes (rerouted, never dropped). Every survivor's
   final report must NAME the wedged rank in its eviction verdict.

Run by scripts/ci.sh; also a manual repro tool:

    python scripts/serving_smoke.py
    python scripts/serving_smoke.py --np 4 --clients 8
"""
from __future__ import annotations

import argparse
import http.client
import json
import os
import socket
import subprocess
import sys
import tempfile
import textwrap
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORKER = textwrap.dedent("""
    import json, os, sys, threading, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import fault_injection
    from horovod_tpu.serving.weights import CheckpointWeightSource

    hvd.init()

    # The wedge trigger: once the parent touches the trigger file, the
    # armed rank's `wedge:step=2` rule fires within ~0.1s (heartbeats
    # stop, every I/O parks, the process stays alive).
    trigger = os.environ.get("SERVE_WEDGE_TRIGGER", "")

    def ticker():
        while True:
            time.sleep(0.05)
            if trigger and os.path.exists(trigger):
                fault_injection.advance_step()

    threading.Thread(target=ticker, daemon=True).start()

    def to_weights(step, objects, trees):
        return {"w": float(np.asarray(trees["w"][0]))}

    def model_fn(weights, payloads):
        return [weights["w"] * float(p) for p in payloads]

    source = CheckpointWeightSource(os.environ["SERVE_CKPT_DIR"],
                                    to_weights=to_weights)
    port = int(os.environ["SERVE_PORT"]) if hvd.rank() == 0 else None
    report_file = os.environ["SERVE_REPORT_FILE"]
    try:
        report = hvd.serving.serve(model_fn, weights={"w": 2.0},
                                   weight_source=source, port=port,
                                   tick_seconds=0.1)
        with open(report_file, "w") as f:
            json.dump(report, f)
        hvd.shutdown()
        sys.exit(0)
    except Exception as e:
        with open(report_file, "w") as f:
            json.dump({"error": str(e)}, f)
        print(f"rank {hvd.rank()}: serve failed: {e}", flush=True)
        sys.exit(42)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _infer(port: int, value: float, timeout: float = 90.0):
    t0 = time.monotonic()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/infer", json.dumps({"inputs": value}))
        r = conn.getresponse()
        body = json.loads(r.read())
        return time.monotonic() - t0, r.status, body
    finally:
        conn.close()


def _client_burst(port: int, n_clients: int, per_client: int,
                  value: float = 1.0, until=None):
    """N concurrent clients. Fixed work (`per_client` requests each),
    or — when `until` is a threading.Event — continuous traffic until
    the event fires (each client still sends at least `per_client`).
    Returns (latencies, [(status, body)...], errors) across all."""
    lats, results, errors = [], [], []
    lock = threading.Lock()

    def client(ci):
        sent = 0
        while True:
            if until is None:
                if sent >= per_client:
                    return
            elif sent >= per_client and until.is_set():
                return
            try:
                lat, status, body = _infer(port, value)
                with lock:
                    lats.append(lat)
                    results.append((status, body))
            except Exception as e:  # connection trouble = a dropped request
                with lock:
                    errors.append(str(e))
            sent += 1

    threads = [threading.Thread(target=client, args=(ci,), daemon=True)
               for ci in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return lats, results, errors


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return float("nan")
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def _get_view(port: int, path: str) -> dict:
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path)
        return json.loads(conn.getresponse().read())
    finally:
        conn.close()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--np", dest="np_", type=int, default=4)
    ap.add_argument("--clients", type=int, default=6,
                    help="concurrent client threads (default 6)")
    ap.add_argument("--per-client", type=int, default=8,
                    help="requests per client per phase")
    ap.add_argument("--wedge-rank", type=int, default=2)
    ap.add_argument("--hb-interval", type=float, default=0.5)
    ap.add_argument("--hb-miss", type=int, default=4)
    ap.add_argument("--skip-wedge", action="store_true",
                    help="phases 1-2 only (no chaos)")
    args = ap.parse_args()
    import numpy as np

    from horovod_tpu.runner.hosts import get_host_assignments, parse_hosts
    from horovod_tpu.runner.launch import slot_env
    from horovod_tpu.runner.rendezvous_server import RendezvousServer
    from horovod_tpu.serving.weights import publish_weights

    serve_port = _free_port()
    metrics_port = _free_port()
    server = RendezvousServer()
    rdv_port = server.start()
    ok = True
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "worker.py")
        with open(script, "w") as f:
            f.write(WORKER)
        ckpt_dir = os.path.join(td, "ckpt")
        os.makedirs(ckpt_dir)
        trigger = os.path.join(td, "wedge_now")
        report_files = {}
        slots = get_host_assignments(
            parse_hosts(f"localhost:{args.np_}"), args.np_)
        procs = {}
        try:
            for slot in slots:
                env = dict(os.environ)
                env.update(slot_env(slot, "127.0.0.1", rdv_port))
                env["PYTHONPATH"] = REPO
                env["HVDRUN_FORCE_LOCAL"] = "1"
                env["HOROVOD_CYCLE_TIME"] = "1"
                env["HOROVOD_TCP_TIMEOUT_SECONDS"] = "0"  # liveness only
                env["HOROVOD_HEARTBEAT_INTERVAL_SECONDS"] = str(
                    args.hb_interval)
                env["HOROVOD_HEARTBEAT_MISS_LIMIT"] = str(args.hb_miss)
                env["HOROVOD_SERVING_MAX_DELAY_MS"] = "5"
                env["HOROVOD_SERVING_WEIGHT_REFRESH_SECONDS"] = "0.2"
                env["SERVE_PORT"] = str(serve_port)
                env["SERVE_CKPT_DIR"] = ckpt_dir
                report_files[slot.rank] = os.path.join(
                    td, f"report_{slot.rank}.json")
                env["SERVE_REPORT_FILE"] = report_files[slot.rank]
                env.pop("HOROVOD_FAULT_INJECT", None)
                env.pop("SERVE_WEDGE_TRIGGER", None)
                if slot.rank == 0:
                    env["HOROVOD_METRICS_PORT"] = str(metrics_port)
                if not args.skip_wedge and slot.rank == args.wedge_rank:
                    env["HOROVOD_FAULT_INJECT"] = "wedge:step=2"
                    env["SERVE_WEDGE_TRIGGER"] = trigger
                procs[slot.rank] = subprocess.Popen(
                    [sys.executable, script], env=env)
            print(f"spawned {args.np_} serving workers; front door "
                  f":{serve_port}, metrics :{metrics_port}", flush=True)

            # Wait for the front door.
            deadline = time.monotonic() + 120
            while True:
                try:
                    lat, status, body = _infer(serve_port, 1.0)
                    assert status == 200 and body["output"] == 2.0, (
                        status, body)
                    break
                except (ConnectionRefusedError, OSError):
                    if time.monotonic() > deadline:
                        raise RuntimeError("front door never came up")
                    time.sleep(0.25)

            # -- phase 1: concurrent baseline ---------------------------
            lats, results, errors = _client_burst(
                serve_port, args.clients, args.per_client)
            assert not errors, errors
            bad = [r for r in results if r[0] != 200]
            assert not bad, bad[:3]
            assert all(r[1]["output"] == 2.0 for r in results), results[:3]
            lats.sort()
            p50, p99 = _quantile(lats, 0.5), _quantile(lats, 0.99)
            assert 0 < p50 <= p99 < 90, (p50, p99)
            print(f"phase 1 OK: {len(results)} requests, "
                  f"p50={p50*1e3:.1f}ms p99={p99*1e3:.1f}ms", flush=True)

            # -- phase 2: weight refresh mid-traffic --------------------
            # Traffic runs CONTINUOUSLY until the swap is observed, so
            # the result set provably straddles the flip.
            swap_results = []
            swap_errors = []
            swap_done = threading.Event()

            def traffic():
                _, res, errs = _client_burst(
                    serve_port, args.clients, args.per_client,
                    until=swap_done)
                swap_results.extend(res)
                swap_errors.extend(errs)

            t = threading.Thread(target=traffic, daemon=True)
            t.start()
            try:
                time.sleep(0.2)  # land the publish genuinely mid-traffic
                publish_weights(ckpt_dir, 10, {"w": [np.float64(5.0)]})
                deadline = time.monotonic() + 60
                while True:
                    _, status, body = _infer(serve_port, 1.0)
                    assert status == 200, body
                    if body["output"] == 5.0 and body["weight_step"] == 10:
                        break
                    assert time.monotonic() < deadline, (
                        "weights never swapped", body)
                    time.sleep(0.1)
            finally:
                swap_done.set()  # an assert must not leave traffic spinning
            t.join()
            assert not swap_errors, swap_errors
            bad = [r for r in swap_results if r[0] != 200]
            assert not bad, bad[:3]  # ZERO dropped requests across the swap
            seen = {(r[1]["output"], r[1]["weight_step"])
                    for r in swap_results}
            # Every response is one of the two weight versions, and the
            # post-swap version provably appeared IN the burst. (The
            # pre-swap version is all but guaranteed by the 0.2s head
            # start; its absence on a pathologically loaded box is not
            # a correctness failure, so it only warns.)
            assert seen <= {(2.0, -1), (5.0, 10)}, seen
            assert (5.0, 10) in seen, seen
            if (2.0, -1) not in seen:
                print("WARN: no pre-swap response landed in the burst "
                      "(box too loaded?)", flush=True)
            print(f"phase 2 OK: swap mid-traffic, {len(swap_results)} "
                  f"requests all 200, responses straddle the flip: "
                  f"{sorted(seen)}", flush=True)

            # -- phase 3: wedge one replica mid-traffic -----------------
            if not args.skip_wedge:
                wedge_results = []
                wedge_errors = []
                wedge_done = threading.Event()

                def wedge_traffic():
                    _, res, errs = _client_burst(
                        serve_port, args.clients, args.per_client,
                        value=3.0, until=wedge_done)
                    wedge_results.extend(res)
                    wedge_errors.extend(errs)

                t = threading.Thread(target=wedge_traffic, daemon=True)
                t.start()
                try:
                    time.sleep(0.2)
                    with open(trigger, "w") as f:
                        f.write("now")
                    # Keep traffic flowing until the eviction is
                    # visible on the /serving view, so requests
                    # provably span the outage + re-mesh.
                    deadline = time.monotonic() + 90
                    while True:
                        try:
                            # The metrics endpoint blinks during the
                            # re-mesh (old engine's exporters down, new
                            # engine's not yet up on the same port) —
                            # retry through it. Wait for the POST-re-
                            # mesh state (shrunken world), not just the
                            # verdict: the verdict lands first, while
                            # the old membership is still visible.
                            view = _get_view(metrics_port, "/serving")
                            if (view.get("evictions") == 1
                                    and view.get("world")
                                    == args.np_ - 1):
                                break
                        except OSError:
                            view = None
                        assert time.monotonic() < deadline, view
                        time.sleep(0.5)
                finally:
                    wedge_done.set()
                t.join()
                assert not wedge_errors, wedge_errors
                bad = [r for r in wedge_results if r[0] != 200]
                assert not bad, bad[:3]  # accepted => completed, rerouted
                assert all(r[1]["output"] == 15.0 for r in wedge_results)
                assert view["world"] == args.np_ - 1, view
                assert args.wedge_rank not in view["members"], view
                assert any(f"rank {args.wedge_rank}" in v
                           for v in view["verdicts"]), view
                status_doc = _get_view(metrics_port, "/status")
                assert status_doc.get("serving", {}).get("world") == (
                    args.np_ - 1), status_doc.get("serving")
                print(f"phase 3 OK: rank {args.wedge_rank} evicted, "
                      f"{len(wedge_results)} requests all 200 on the "
                      f"survivors", flush=True)

            # -- graceful stop ------------------------------------------
            conn = http.client.HTTPConnection("127.0.0.1", serve_port,
                                              timeout=30)
            conn.request("POST", "/admin/stop")
            assert conn.getresponse().status == 200
            conn.close()
            survivors = [r for r in procs
                         if args.skip_wedge or r != args.wedge_rank]
            for r in survivors:
                rc = procs[r].wait(timeout=120)
                if rc != 0:
                    print(f"FAIL: rank {r} exited {rc}", flush=True)
                    ok = False
            verdict_rows = []
            for r in survivors:
                with open(report_files[r]) as f:
                    rep = json.load(f)
                verdict_rows.append((r, rep))
                if not args.skip_wedge:
                    named = any(f"rank {args.wedge_rank}" in v
                                for v in rep.get("verdicts", []))
                    if not named:
                        print(f"FAIL: rank {r} did not name the wedged "
                              f"rank: {rep}", flush=True)
                        ok = False
            if not args.skip_wedge:
                if procs[args.wedge_rank].poll() is not None:
                    print("FAIL: wedged rank DIED (a wedge must keep the "
                          "process alive)", flush=True)
                    ok = False
                else:
                    print(f"wedged rank {args.wedge_rank} alive and "
                          "frozen, as intended (killing it now)",
                          flush=True)
            for r, rep in verdict_rows:
                print(f"  rank {r}: rounds={rep.get('rounds')} "
                      f"forwarded={rep.get('forwarded')} "
                      f"weight_step={rep.get('weight_step')} "
                      f"verdicts={rep.get('verdicts')}", flush=True)
            print(json.dumps({
                "metric": "serving_smoke",
                "p50_ms": round(p50 * 1e3, 2),
                "p99_ms": round(p99 * 1e3, 2),
                "requests": len(results) + len(swap_results),
            }))
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
            server.stop()
    print("PASS" if ok else "FAIL", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
