#!/usr/bin/env python
"""Serving-plane smoke: the ROADMAP item-4 acceptance scenario, end to
end on one box (docs/serving.md).

A real 4-rank mesh serves HTTP inference through the rank-0 front door
while this parent process plays N concurrent clients. Three phases, one
continuous job:

1. **Baseline** — concurrent clients, measured p50/p99 request latency
   asserted finite and sane, every request 200.
2. **Weight refresh mid-traffic** — the parent publishes a new weight
   version into the watched checkpoint dir (the durability-plane
   layout); replicas background-load and hot-swap between batches.
   ZERO dropped requests across the swap, and post-swap responses
   provably reflect the new weights (the output value and the
   `weight_step` echo both flip).
3. **Wedge one replica** — a non-zero rank freezes (process alive,
   sockets open, heartbeats stop) under UNBOUNDED socket timeouts; the
   liveness plane declares it dead, the serving plane evicts it and
   re-meshes the survivors, and every request accepted during the
   outage still completes (rerouted, never dropped). Every survivor's
   final report must NAME the wedged rank in its eviction verdict.

Then a SECOND fresh mesh exercises the fleet features
(docs/serving.md "Redundant front doors"):

4. **Kill the ACTIVE front door mid-traffic** — two doors
   (``HOROVOD_SERVING_DOORS=2``), continuous traffic through the
   STANDBY door (forwarded over the round protocol; a streamed request
   proves chunked ndjson end to end first), and a
   ``killdoor:after=N`` chaos rule hard-kills rank 0 after N
   admissions. The standby door must win the election (epoch bump,
   verdict naming rank 0 on its ``/serving``) and EVERY request
   accepted at the surviving door must answer 200 — zero loss.
5. **Closed-loop autoscaler** — with
   ``HOROVOD_SERVING_AUTOSCALE_INTERVAL_SECONDS=1``, idle traffic
   shrinks the mesh toward the door floor (victims park), a 6-client
   burst grows it back (parked ranks rejoin), p99 stays under 30s,
   zero non-200, and ``serving.scale`` + ``serving.door_elected``
   appear in the lifecycle journal.

Run by scripts/ci.sh; also a manual repro tool:

    python scripts/serving_smoke.py
    python scripts/serving_smoke.py --np 4 --clients 8
    python scripts/serving_smoke.py --fleet-only   # phases 4-5 only
"""
from __future__ import annotations

import argparse
import http.client
import json
import os
import socket
import subprocess
import sys
import tempfile
import textwrap
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORKER = textwrap.dedent("""
    import json, os, sys, threading, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import fault_injection
    from horovod_tpu.serving.weights import CheckpointWeightSource

    hvd.init()

    # The wedge trigger: once the parent touches the trigger file, the
    # armed rank's `wedge:step=2` rule fires within ~0.1s (heartbeats
    # stop, every I/O parks, the process stays alive).
    trigger = os.environ.get("SERVE_WEDGE_TRIGGER", "")

    def ticker():
        while True:
            time.sleep(0.05)
            if trigger and os.path.exists(trigger):
                fault_injection.advance_step()

    threading.Thread(target=ticker, daemon=True).start()

    def to_weights(step, objects, trees):
        return {"w": float(np.asarray(trees["w"][0]))}

    fwd_sleep = float(os.environ.get("SERVE_FORWARD_SLEEP", "0"))

    def model_fn(weights, payloads):
        if fwd_sleep:
            time.sleep(fwd_sleep * max(len(payloads), 1))
        return [weights["w"] * float(p) for p in payloads]

    source = CheckpointWeightSource(os.environ["SERVE_CKPT_DIR"],
                                    to_weights=to_weights)
    # Door ranks carry their own SERVE_PORT; non-door ranks never open
    # a frontend so the value (or its absence) is inert for them.
    port = (int(os.environ["SERVE_PORT"])
            if os.environ.get("SERVE_PORT") else None)
    report_file = os.environ["SERVE_REPORT_FILE"]
    try:
        report = hvd.serving.serve(model_fn, weights={"w": 2.0},
                                   weight_source=source, port=port,
                                   tick_seconds=0.1)
        with open(report_file, "w") as f:
            json.dump(report, f)
        try:
            hvd.shutdown()
        except Exception:
            pass  # a parked rank stopped while de-initialized
        sys.exit(0)
    except Exception as e:
        with open(report_file, "w") as f:
            json.dump({"error": str(e)}, f)
        rank = os.environ.get("HOROVOD_RANK", "?")
        print(f"rank {rank}: serve failed: {e}", flush=True)
        sys.exit(42)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _infer(port: int, value: float, timeout: float = 90.0):
    t0 = time.monotonic()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/infer", json.dumps({"inputs": value}))
        r = conn.getresponse()
        body = json.loads(r.read())
        return time.monotonic() - t0, r.status, body
    finally:
        conn.close()


def _infer_stream(port: int, value: float, chunks: int,
                  timeout: float = 90.0):
    """One streamed inference; returns (status, content-type, frames).
    http.client undoes the chunked transfer-encoding; the body is
    newline-delimited JSON frames (docs/serving.md "Streaming")."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/infer", json.dumps(
            {"inputs": value, "stream": True, "chunks": chunks}))
        r = conn.getresponse()
        body = r.read().decode()
        frames = [json.loads(ln) for ln in body.splitlines()
                  if ln.strip()]
        return r.status, r.getheader("Content-Type", ""), frames
    finally:
        conn.close()


def _client_burst(port: int, n_clients: int, per_client: int,
                  value: float = 1.0, until=None):
    """N concurrent clients. Fixed work (`per_client` requests each),
    or — when `until` is a threading.Event — continuous traffic until
    the event fires (each client still sends at least `per_client`).
    Returns (latencies, [(status, body)...], errors) across all."""
    lats, results, errors = [], [], []
    lock = threading.Lock()

    def client(ci):
        sent = 0
        while True:
            if until is None:
                if sent >= per_client:
                    return
            elif sent >= per_client and until.is_set():
                return
            try:
                lat, status, body = _infer(port, value)
                err = (body.get("error", "")
                       if isinstance(body, dict) else "")
                if status in (429, 503) and "retry" in err:
                    # Documented-retryable rejection (backpressure or a
                    # transiently stale door) — NOT an accepted request,
                    # so it cannot count against zero-loss.
                    time.sleep(0.05)
                    continue
                with lock:
                    lats.append(lat)
                    results.append((status, body))
            except Exception as e:  # connection trouble = a dropped request
                with lock:
                    errors.append(str(e))
            sent += 1

    threads = [threading.Thread(target=client, args=(ci,), daemon=True)
               for ci in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return lats, results, errors


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return float("nan")
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def _get_view(port: int, path: str, retry_s: float = 45.0) -> dict:
    # A re-mesh re-inits the engine (metrics server included): a
    # connection refused mid-poll is a transient, not a verdict.
    deadline = time.monotonic() + retry_s
    while True:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            conn.request("GET", path)
            return json.loads(conn.getresponse().read())
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.25)
        finally:
            conn.close()


def run_base(args) -> bool:
    """Phases 1-3: one mesh, a single front door."""
    import numpy as np

    from horovod_tpu.runner.hosts import get_host_assignments, parse_hosts
    from horovod_tpu.runner.launch import slot_env
    from horovod_tpu.runner.rendezvous_server import RendezvousServer
    from horovod_tpu.serving.weights import publish_weights

    serve_port = _free_port()
    metrics_port = _free_port()
    server = RendezvousServer()
    rdv_port = server.start()
    ok = True
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "worker.py")
        with open(script, "w") as f:
            f.write(WORKER)
        ckpt_dir = os.path.join(td, "ckpt")
        os.makedirs(ckpt_dir)
        trigger = os.path.join(td, "wedge_now")
        report_files = {}
        slots = get_host_assignments(
            parse_hosts(f"localhost:{args.np_}"), args.np_)
        procs = {}
        try:
            for slot in slots:
                env = dict(os.environ)
                env.update(slot_env(slot, "127.0.0.1", rdv_port))
                env["PYTHONPATH"] = REPO
                env["HVDRUN_FORCE_LOCAL"] = "1"
                env["HOROVOD_CYCLE_TIME"] = "1"
                env["HOROVOD_TCP_TIMEOUT_SECONDS"] = "0"  # liveness only
                env["HOROVOD_HEARTBEAT_INTERVAL_SECONDS"] = str(
                    args.hb_interval)
                env["HOROVOD_HEARTBEAT_MISS_LIMIT"] = str(args.hb_miss)
                env["HOROVOD_SERVING_MAX_DELAY_MS"] = "5"
                env["HOROVOD_SERVING_WEIGHT_REFRESH_SECONDS"] = "0.2"
                env["SERVE_PORT"] = str(serve_port)
                env["SERVE_CKPT_DIR"] = ckpt_dir
                report_files[slot.rank] = os.path.join(
                    td, f"report_{slot.rank}.json")
                env["SERVE_REPORT_FILE"] = report_files[slot.rank]
                env.pop("HOROVOD_FAULT_INJECT", None)
                env.pop("SERVE_WEDGE_TRIGGER", None)
                if slot.rank == 0:
                    env["HOROVOD_METRICS_PORT"] = str(metrics_port)
                if not args.skip_wedge and slot.rank == args.wedge_rank:
                    env["HOROVOD_FAULT_INJECT"] = "wedge:step=2"
                    env["SERVE_WEDGE_TRIGGER"] = trigger
                procs[slot.rank] = subprocess.Popen(
                    [sys.executable, script], env=env)
            print(f"spawned {args.np_} serving workers; front door "
                  f":{serve_port}, metrics :{metrics_port}", flush=True)

            # Wait for the front door.
            deadline = time.monotonic() + 120
            while True:
                try:
                    lat, status, body = _infer(serve_port, 1.0)
                    assert status == 200 and body["output"] == 2.0, (
                        status, body)
                    break
                except (ConnectionRefusedError, OSError):
                    if time.monotonic() > deadline:
                        raise RuntimeError("front door never came up")
                    time.sleep(0.25)

            # -- phase 1: concurrent baseline ---------------------------
            lats, results, errors = _client_burst(
                serve_port, args.clients, args.per_client)
            assert not errors, errors
            bad = [r for r in results if r[0] != 200]
            assert not bad, bad[:3]
            assert all(r[1]["output"] == 2.0 for r in results), results[:3]
            lats.sort()
            p50, p99 = _quantile(lats, 0.5), _quantile(lats, 0.99)
            assert 0 < p50 <= p99 < 90, (p50, p99)
            print(f"phase 1 OK: {len(results)} requests, "
                  f"p50={p50*1e3:.1f}ms p99={p99*1e3:.1f}ms", flush=True)

            # -- phase 2: weight refresh mid-traffic --------------------
            # Traffic runs CONTINUOUSLY until the swap is observed, so
            # the result set provably straddles the flip.
            swap_results = []
            swap_errors = []
            swap_done = threading.Event()

            def traffic():
                _, res, errs = _client_burst(
                    serve_port, args.clients, args.per_client,
                    until=swap_done)
                swap_results.extend(res)
                swap_errors.extend(errs)

            t = threading.Thread(target=traffic, daemon=True)
            t.start()
            try:
                time.sleep(0.2)  # land the publish genuinely mid-traffic
                publish_weights(ckpt_dir, 10, {"w": [np.float64(5.0)]})
                deadline = time.monotonic() + 60
                while True:
                    _, status, body = _infer(serve_port, 1.0)
                    assert status == 200, body
                    if body["output"] == 5.0 and body["weight_step"] == 10:
                        break
                    assert time.monotonic() < deadline, (
                        "weights never swapped", body)
                    time.sleep(0.1)
            finally:
                swap_done.set()  # an assert must not leave traffic spinning
            t.join()
            assert not swap_errors, swap_errors
            bad = [r for r in swap_results if r[0] != 200]
            assert not bad, bad[:3]  # ZERO dropped requests across the swap
            seen = {(r[1]["output"], r[1]["weight_step"])
                    for r in swap_results}
            # Every response is one of the two weight versions, and the
            # post-swap version provably appeared IN the burst. (The
            # pre-swap version is all but guaranteed by the 0.2s head
            # start; its absence on a pathologically loaded box is not
            # a correctness failure, so it only warns.)
            assert seen <= {(2.0, -1), (5.0, 10)}, seen
            assert (5.0, 10) in seen, seen
            if (2.0, -1) not in seen:
                print("WARN: no pre-swap response landed in the burst "
                      "(box too loaded?)", flush=True)
            print(f"phase 2 OK: swap mid-traffic, {len(swap_results)} "
                  f"requests all 200, responses straddle the flip: "
                  f"{sorted(seen)}", flush=True)

            # -- phase 3: wedge one replica mid-traffic -----------------
            if not args.skip_wedge:
                wedge_results = []
                wedge_errors = []
                wedge_done = threading.Event()

                def wedge_traffic():
                    _, res, errs = _client_burst(
                        serve_port, args.clients, args.per_client,
                        value=3.0, until=wedge_done)
                    wedge_results.extend(res)
                    wedge_errors.extend(errs)

                t = threading.Thread(target=wedge_traffic, daemon=True)
                t.start()
                try:
                    time.sleep(0.2)
                    with open(trigger, "w") as f:
                        f.write("now")
                    # Keep traffic flowing until the eviction is
                    # visible on the /serving view, so requests
                    # provably span the outage + re-mesh.
                    deadline = time.monotonic() + 90
                    while True:
                        try:
                            # The metrics endpoint blinks during the
                            # re-mesh (old engine's exporters down, new
                            # engine's not yet up on the same port) —
                            # retry through it. Wait for the POST-re-
                            # mesh state (shrunken world), not just the
                            # verdict: the verdict lands first, while
                            # the old membership is still visible.
                            view = _get_view(metrics_port, "/serving")
                            if (view.get("evictions") == 1
                                    and view.get("world")
                                    == args.np_ - 1):
                                break
                        except OSError:
                            view = None
                        assert time.monotonic() < deadline, view
                        time.sleep(0.5)
                finally:
                    wedge_done.set()
                t.join()
                assert not wedge_errors, wedge_errors
                bad = [r for r in wedge_results if r[0] != 200]
                assert not bad, bad[:3]  # accepted => completed, rerouted
                assert all(r[1]["output"] == 15.0 for r in wedge_results)
                assert view["world"] == args.np_ - 1, view
                assert args.wedge_rank not in view["members"], view
                assert any(f"rank {args.wedge_rank}" in v
                           for v in view["verdicts"]), view
                status_doc = _get_view(metrics_port, "/status")
                assert status_doc.get("serving", {}).get("world") == (
                    args.np_ - 1), status_doc.get("serving")
                print(f"phase 3 OK: rank {args.wedge_rank} evicted, "
                      f"{len(wedge_results)} requests all 200 on the "
                      f"survivors", flush=True)

            # -- graceful stop ------------------------------------------
            conn = http.client.HTTPConnection("127.0.0.1", serve_port,
                                              timeout=30)
            conn.request("POST", "/admin/stop")
            assert conn.getresponse().status == 200
            conn.close()
            survivors = [r for r in procs
                         if args.skip_wedge or r != args.wedge_rank]
            for r in survivors:
                rc = procs[r].wait(timeout=120)
                if rc != 0:
                    print(f"FAIL: rank {r} exited {rc}", flush=True)
                    ok = False
            verdict_rows = []
            for r in survivors:
                with open(report_files[r]) as f:
                    rep = json.load(f)
                verdict_rows.append((r, rep))
                if not args.skip_wedge:
                    named = any(f"rank {args.wedge_rank}" in v
                                for v in rep.get("verdicts", []))
                    if not named:
                        print(f"FAIL: rank {r} did not name the wedged "
                              f"rank: {rep}", flush=True)
                        ok = False
            if not args.skip_wedge:
                if procs[args.wedge_rank].poll() is not None:
                    print("FAIL: wedged rank DIED (a wedge must keep the "
                          "process alive)", flush=True)
                    ok = False
                else:
                    print(f"wedged rank {args.wedge_rank} alive and "
                          "frozen, as intended (killing it now)",
                          flush=True)
            for r, rep in verdict_rows:
                print(f"  rank {r}: rounds={rep.get('rounds')} "
                      f"forwarded={rep.get('forwarded')} "
                      f"weight_step={rep.get('weight_step')} "
                      f"verdicts={rep.get('verdicts')}", flush=True)
            print(json.dumps({
                "metric": "serving_smoke",
                "p50_ms": round(p50 * 1e3, 2),
                "p99_ms": round(p99 * 1e3, 2),
                "requests": len(results) + len(swap_results),
            }))
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
            server.stop()
    return ok


def run_fleet(args) -> bool:
    """Phases 4-5: redundant doors + killdoor failover + streaming +
    the closed-loop serving autoscaler, on a FRESH mesh (the base mesh
    already drained; fleet semantics deserve clean state)."""
    from horovod_tpu.runner.hosts import get_host_assignments, parse_hosts
    from horovod_tpu.runner.launch import slot_env
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    np_ = max(args.np_, 4)
    door_ports = [_free_port(), _free_port()]
    metrics_ports = [_free_port(), _free_port()]
    server = RendezvousServer()
    rdv_port = server.start()
    ok = True
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "worker.py")
        with open(script, "w") as f:
            f.write(WORKER)
        ckpt_dir = os.path.join(td, "ckpt")
        os.makedirs(ckpt_dir)
        report_files = {}
        slots = get_host_assignments(
            parse_hosts(f"localhost:{np_}"), np_)
        procs = {}
        try:
            for slot in slots:
                env = dict(os.environ)
                env.update(slot_env(slot, "127.0.0.1", rdv_port))
                env["PYTHONPATH"] = REPO
                env["HVDRUN_FORCE_LOCAL"] = "1"
                env["HOROVOD_CYCLE_TIME"] = "1"
                env["HOROVOD_TCP_TIMEOUT_SECONDS"] = "0"  # liveness only
                env["HOROVOD_HEARTBEAT_INTERVAL_SECONDS"] = str(
                    args.hb_interval)
                env["HOROVOD_HEARTBEAT_MISS_LIMIT"] = str(args.hb_miss)
                env["HOROVOD_SERVING_MAX_DELAY_MS"] = "5"
                env["HOROVOD_SERVING_DOORS"] = "2"
                env["HOROVOD_SERVING_AUTOSCALE_INTERVAL_SECONDS"] = "1.0"
                # A touch of model latency so concurrent clients build
                # real backlog — the autoscaler's input signal.
                env["SERVE_FORWARD_SLEEP"] = "0.02"
                env["SERVE_CKPT_DIR"] = ckpt_dir
                report_files[slot.rank] = os.path.join(
                    td, f"fleet_report_{slot.rank}.json")
                env["SERVE_REPORT_FILE"] = report_files[slot.rank]
                env.pop("HOROVOD_FAULT_INJECT", None)
                env.pop("SERVE_WEDGE_TRIGGER", None)
                env.pop("SERVE_PORT", None)
                env.pop("HOROVOD_METRICS_PORT", None)
                if slot.rank < 2:  # the two doors
                    env["SERVE_PORT"] = str(door_ports[slot.rank])
                    env["HOROVOD_METRICS_PORT"] = str(
                        metrics_ports[slot.rank])
                if slot.rank == 0:
                    env["HOROVOD_FAULT_INJECT"] = (
                        f"killdoor:after={args.killdoor_after}")
                procs[slot.rank] = subprocess.Popen(
                    [sys.executable, script], env=env)
            print(f"fleet: spawned {np_} workers; active door "
                  f":{door_ports[0]} (killdoor-armed), standby door "
                  f":{door_ports[1]}", flush=True)

            deadline = time.monotonic() + 120
            for port in door_ports:
                while True:
                    try:
                        _, status, body = _infer(port, 1.0)
                        if status == 200:
                            assert body["output"] == 2.0, body
                            break
                        # 503-stale / 429 while the fleet settles its
                        # first leases: retryable by contract.
                    except (ConnectionRefusedError, OSError):
                        pass
                    if time.monotonic() > deadline:
                        raise RuntimeError(f"door :{port} never came up")
                    time.sleep(0.25)

            # Streaming through the STANDBY door — a FORWARDED stream:
            # chunks ride coordinator commands back to the origin door.
            status, ctype, frames = _infer_stream(door_ports[1], 3.0, 3)
            assert status == 200, (status, frames)
            assert "ndjson" in ctype, ctype
            data = [f for f in frames if not f.get("final")]
            fin = [f for f in frames if f.get("final")]
            assert len(data) >= 2, frames
            assert all("weight_step" in f for f in data), frames
            assert all(f.get("output") == 6.0 for f in data), frames
            assert [f["seq"] for f in data] == list(range(len(data))), (
                frames)
            assert len(fin) == 1 and fin[0].get("status") == "ok", frames
            # Unary stays the default wire shape.
            _, status, body = _infer(door_ports[1], 1.0)
            assert status == 200 and body.get("output") == 2.0, body
            assert "final" not in body, body
            print(f"streaming OK: {len(data)} chunks (each stamped "
                  f"weight_step) + terminal frame through the standby "
                  f"door; unary default intact", flush=True)

            # -- phase 4: kill the ACTIVE door mid-traffic --------------
            t4_results, t4_errors = [], []
            t4_done = threading.Event()

            def t4_traffic():
                _, res, errs = _client_burst(
                    door_ports[1], args.clients, args.per_client,
                    value=1.0, until=t4_done)
                t4_results.extend(res)
                t4_errors.extend(errs)

            t = threading.Thread(target=t4_traffic, daemon=True)
            t.start()
            try:
                time.sleep(0.5)  # the burst is genuinely in flight
                # The metrics endpoint serves on communicator rank 0
                # only: before the kill that is world rank 0; AFTER the
                # failover rank 1 re-inits as rank 0 and its endpoint
                # (metrics_ports[1]) lights up — itself a signal the
                # election happened.
                view0 = _get_view(metrics_ports[0], "/serving")
                w0 = view0["world"]
                # Trip the killdoor: admissions at the ACTIVE door.
                # The killing admission itself gets no response — that
                # connection error is the fault, not a lost request.
                for _ in range(args.killdoor_after + 3):
                    if procs[0].poll() is not None:
                        break
                    try:
                        _infer(door_ports[0], 1.0, timeout=10)
                    except Exception:
                        break
                    time.sleep(0.05)
                assert procs[0].wait(timeout=30) != 0  # died by design
                deadline = time.monotonic() + 90
                while True:
                    try:
                        view = _get_view(metrics_ports[1], "/serving")
                        if (view.get("role") == "coordinator"
                                and view.get("evictions", 0) >= 1
                                and 0 not in view.get("members", [0])):
                            break
                    except OSError:
                        view = None
                    assert time.monotonic() < deadline, view
                    time.sleep(0.5)
            finally:
                t4_done.set()
            t.join()
            assert not t4_errors, t4_errors[:3]
            bad = [r for r in t4_results if r[0] != 200]
            assert not bad, bad[:3]  # accepted at a survivor => answered
            assert view.get("door") == 1, view
            assert view.get("door_epoch", 0) >= 1, view
            # A hard kill surfaces as the finalized transport text
            # ("rank 1: recv from peer 0 failed"): the dead rank shows
            # up as "peer 0".  A liveness verdict would say "rank 0 ...
            # declared dead".  Either way rank 0 must be the one named.
            assert any("peer 0" in v or "rank 0" in v
                       for v in view["verdicts"]), view
            print(f"phase 4 OK: active door killed after "
                  f"{args.killdoor_after} admissions; door 1 won the "
                  f"election (epoch {view['door_epoch']}, world "
                  f"{w0}->{view['world']}), {len(t4_results)} "
                  f"surviving-door requests all 200, verdict names "
                  f"rank 0", flush=True)

            # -- phase 5: the autoscaler closes the loop ----------------
            # Idle: backlog ~0 per replica -> shrink toward the door
            # floor; the victim parks.
            w_now = view["world"]
            deadline = time.monotonic() + 60
            while True:
                try:
                    v = _get_view(metrics_ports[1], "/serving")
                    # Shrink observed — or the mesh already sits at the
                    # door floor with everyone else parked (the idle
                    # window before the kill may have drained it first).
                    if v["world"] < w_now or (
                            v["world"] <= len(v.get("doors", [1]))
                            and v.get("parked")):
                        break
                except OSError:
                    v = None
                assert time.monotonic() < deadline, ("no scale-down", v)
                time.sleep(0.3)
            assert v.get("parked"), v
            print(f"phase 5: idle shrink {w_now} -> {v['world']} "
                  f"(parked {v['parked']})", flush=True)
            # Idle traffic keeps shrinking the mesh all the way to the
            # door floor (min_replicas tracks the live door count).
            # Wait for it to settle there, else the grow check below
            # races a further shrink: capture world=2, mesh shrinks to
            # 1, grows back to 2 — and "> 2" never fires.
            deadline = time.monotonic() + 60
            while v["world"] > len(v.get("doors", [1])):
                assert time.monotonic() < deadline, ("no floor", v)
                time.sleep(0.3)
                v = _get_view(metrics_ports[1], "/serving")
            shrunk = v["world"]

            t5_results, t5_errors, t5_lats = [], [], []
            t5_done = threading.Event()

            def t5_traffic():
                lats, res, errs = _client_burst(
                    door_ports[1], args.clients, args.per_client,
                    value=2.0, until=t5_done)
                t5_lats.extend(lats)
                t5_results.extend(res)
                t5_errors.extend(errs)

            t = threading.Thread(target=t5_traffic, daemon=True)
            t.start()
            grew = False
            try:
                deadline = time.monotonic() + 90
                while True:
                    try:
                        v = _get_view(metrics_ports[1], "/serving")
                        if v["world"] > shrunk:
                            grew = True
                            break
                    except OSError:
                        v = None
                    assert time.monotonic() < deadline, ("no scale-up", v)
                    time.sleep(0.3)
            finally:
                t5_done.set()
            t.join()
            assert grew
            assert not t5_errors, t5_errors[:3]
            bad = [r for r in t5_results if r[0] != 200]
            assert not bad, bad[:3]
            t5_lats.sort()
            p99 = _quantile(t5_lats, 0.99)
            assert p99 < 30.0, p99  # the stated latency bound
            ev = _get_view(metrics_ports[1], "/events")
            rows = ((ev.get("fleet") or {}).get("events")
                    or (ev.get("local") or {}).get("events") or [])
            kinds = {d.get("kind") for d in rows}
            assert "serving.scale" in kinds, kinds
            assert "serving.door_elected" in kinds, kinds
            print(f"phase 5 OK: grow back to {v['world']} under "
                  f"{args.clients}-client traffic; {len(t5_results)} "
                  f"requests all 200, p99={p99*1e3:.1f}ms; "
                  f"serving.scale + serving.door_elected journaled",
                  flush=True)

            # -- graceful stop ------------------------------------------
            conn = http.client.HTTPConnection(
                "127.0.0.1", door_ports[1], timeout=30)
            conn.request("POST", "/admin/stop")
            assert conn.getresponse().status == 200
            conn.close()
            for r in sorted(procs):
                if r == 0:
                    continue  # the killdoor victim
                rc = procs[r].wait(timeout=120)
                if rc != 0:
                    print(f"FAIL: fleet rank {r} exited {rc}",
                          flush=True)
                    ok = False
            print(json.dumps({
                "metric": "serving_fleet_smoke",
                "requests": len(t4_results) + len(t5_results),
                "p99_ms": round(p99 * 1e3, 2),
            }))
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
            server.stop()
    return ok


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--np", dest="np_", type=int, default=4)
    ap.add_argument("--clients", type=int, default=6,
                    help="concurrent client threads (default 6)")
    ap.add_argument("--per-client", type=int, default=8,
                    help="requests per client per phase")
    ap.add_argument("--wedge-rank", type=int, default=2)
    ap.add_argument("--hb-interval", type=float, default=0.5)
    ap.add_argument("--hb-miss", type=int, default=4)
    ap.add_argument("--skip-wedge", action="store_true",
                    help="phases 1-2 only (no chaos)")
    ap.add_argument("--skip-fleet", action="store_true",
                    help="phases 1-3 only (no doors/autoscaler mesh)")
    ap.add_argument("--fleet-only", action="store_true",
                    help="phases 4-5 only")
    ap.add_argument("--killdoor-after", type=int, default=5,
                    help="admissions before the chaos rule kills the "
                         "active door (phase 4)")
    args = ap.parse_args()
    ok = True
    if not args.fleet_only:
        ok = run_base(args) and ok
    if not args.skip_fleet:
        ok = run_fleet(args) and ok
    print("PASS" if ok else "FAIL", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
