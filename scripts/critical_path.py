#!/usr/bin/env python
"""Critical-path / straggler analysis over a merged horovod_tpu trace.

Input: a Chrome/Perfetto trace produced by the tracing plane — the
`/trace` endpoint body, a ``HOROVOD_TRACE_FILE`` dump, or a stitched
``postmortem.json`` (docs/tracing.md). Every X event carries its
collective's trace id in ``args.trace_id`` and its phase in ``cat``
(negotiate / queue / exec / xfer / compute); the process lane (pid) is
the rank.

For each collective (trace id) the analyzer computes:

* wall span (first event start -> last event end, clock-aligned);
* per-phase attribution (how much of the span each category covered,
  summed over ranks — where did the 40 ms go);
* the straggler rank: the rank whose `exec.*` span finished last — the
  rank everyone else's allgather/bcast waited on.

The summary aggregates phase totals and names the worst stragglers
(rank -> how many collectives it finished last, and by how much).

When the trace carries the goodput ledger's ``step`` spans
(docs/goodput.md), collectives are additionally grouped under them:
per step and per rank, total executor communication time is split into
the exposed share the training thread actually waited on (from the
span args) and the overlapped remainder — the ``steps`` section.

    python scripts/critical_path.py trace.json
    python scripts/critical_path.py postmortem.json --top 10
    curl -s localhost:9099/trace | python scripts/critical_path.py -
    python scripts/critical_path.py --from-url http://localhost:9099

``--from-url`` pulls the live ``/trace`` endpoint of a RUNNING job
(the rank-0 metrics server, docs/health.md) — straggler attribution
without waiting for a shutdown dump. A bare host:port or a full URL
(with or without the /trace path) are all accepted.
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.utils import chrome_trace  # noqa: E402


def load_events(path: str):
    if path == "-":
        doc = json.load(sys.stdin)
    else:
        doc = chrome_trace.read_trace_file(path)
    return chrome_trace.trace_events(doc), doc


def fetch_url(url: str, timeout: float = 30.0):
    """GET a live /trace endpoint. Accepts host:port, http://host:port,
    or a full .../trace URL."""
    import urllib.request

    if "://" not in url:
        url = "http://" + url
    if not url.rstrip("/").endswith("/trace"):
        url = url.rstrip("/") + "/trace"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        doc = json.load(resp)
    return chrome_trace.trace_events(doc), doc


def analyze_steps(events, top: int = 5):
    """Group collectives under the goodput ledger's `step` spans
    (docs/goodput.md): for each demarcated step on each rank, the
    executor time its collectives spent inside the step window is that
    step's total communication; the ledger's exposed-comm share (in
    the span args) is the part the training thread actually waited on;
    the difference is overlapped — comm that cost nothing."""
    step_spans = []
    exec_by_rank = collections.defaultdict(list)
    for e in events:
        if e.get("ph") != "X":
            continue
        name = str(e.get("name", ""))
        if name == "step" and e.get("cat") == "step":
            step_spans.append(e)
        elif name.startswith("exec.") and name != "exec.queue_wait" \
                and (e.get("args") or {}).get("trace_id"):
            exec_by_rank[e.get("pid")].append(
                (e["ts"], e["ts"] + e.get("dur", 0), e.get("dur", 0)))
    if not step_spans:
        return None
    steps = []
    per_rank = collections.defaultdict(
        lambda: {"steps": 0, "exposed_us": 0.0, "comm_us": 0.0,
                 "overlapped_us": 0.0})
    for e in step_spans:
        rank = e.get("pid")
        t0, t1 = e["ts"], e["ts"] + e.get("dur", 0)
        args = e.get("args") or {}
        exposed_us = float(args.get("exposed_comm_ms", 0.0)) * 1e3
        comm_us = sum(
            max(min(b, t1) - max(a, t0), 0.0)
            for a, b, _ in exec_by_rank.get(rank, ())
            if a < t1 and b > t0)
        overlapped_us = max(comm_us - exposed_us, 0.0)
        pr = per_rank[rank]
        pr["steps"] += 1
        pr["exposed_us"] += exposed_us
        pr["comm_us"] += comm_us
        pr["overlapped_us"] += overlapped_us
        steps.append({
            "rank": rank,
            "step": args.get("step"),
            "span_us": round(t1 - t0, 1),
            "comm_us": round(comm_us, 1),
            "exposed_us": round(exposed_us, 1),
            "overlapped_us": round(overlapped_us, 1),
        })
    steps.sort(key=lambda s: -s["exposed_us"])
    return {
        "steps_analyzed": len(steps),
        "per_rank": {
            str(r): {k: (v if k == "steps" else round(v, 1))
                     for k, v in d.items()}
            for r, d in sorted(per_rank.items())},
        "worst_exposed_steps": steps[:top],
    }


def analyze(events, top: int = 5):
    by_trace = collections.defaultdict(list)
    for e in events:
        if e.get("ph") != "X":
            continue
        tid = (e.get("args") or {}).get("trace_id")
        if not tid:
            continue  # control-plane/heartbeat spans: no collective
        by_trace[tid].append(e)

    collectives = []
    phase_totals = collections.Counter()
    straggler_counts = collections.Counter()
    straggler_margin_us = collections.Counter()
    for trace_id, evs in by_trace.items():
        t0 = min(e["ts"] for e in evs)
        t1 = max(e["ts"] + e.get("dur", 0) for e in evs)
        ranks = sorted({e.get("pid") for e in evs})
        phases = collections.Counter()
        for e in evs:
            phases[e.get("cat", "?")] += e.get("dur", 0)
            phase_totals[e.get("cat", "?")] += e.get("dur", 0)
        # Straggler: the rank whose executor span ends last. Fall back
        # to any span when a rank's exec events were overwritten.
        exec_end = {}
        for e in evs:
            if str(e.get("name", "")).startswith("exec."):
                end = e["ts"] + e.get("dur", 0)
                r = e.get("pid")
                exec_end[r] = max(exec_end.get(r, 0), end)
        straggler = None
        margin = 0.0
        if len(exec_end) > 1:
            ordered = sorted(exec_end.items(), key=lambda kv: kv[1])
            straggler = ordered[-1][0]
            margin = ordered[-1][1] - ordered[-2][1]
            straggler_counts[straggler] += 1
            straggler_margin_us[straggler] += margin
        names = [e["name"] for e in evs
                 if str(e.get("name", "")).startswith("exec.")
                 and e["name"] != "exec.queue_wait"]
        collectives.append({
            "trace_id": trace_id,
            "op": names[0] if names else "?",
            "ranks": ranks,
            "span_us": round(t1 - t0, 1),
            "phases_us": {k: round(v, 1) for k, v in phases.most_common()},
            "straggler_rank": straggler,
            "straggler_margin_us": round(margin, 1),
        })

    collectives.sort(key=lambda c: -c["span_us"])
    total = sum(phase_totals.values()) or 1.0
    steps = analyze_steps(events, top=top)
    return {
        "collectives_analyzed": len(collectives),
        **({"steps": steps} if steps else {}),
        "phase_attribution_us": {
            k: round(v, 1) for k, v in phase_totals.most_common()},
        "phase_attribution_pct": {
            k: round(100.0 * v / total, 1)
            for k, v in phase_totals.most_common()},
        "stragglers": {
            str(r): {"times_last": n,
                     "total_margin_us": round(straggler_margin_us[r], 1)}
            for r, n in straggler_counts.most_common()},
        "slowest": collectives[:top],
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="?",
                    help="merged trace JSON ('-' for stdin)")
    ap.add_argument("--from-url", dest="from_url",
                    help="pull the live /trace endpoint of a running "
                         "job (host:port or URL) instead of a file")
    ap.add_argument("--top", type=int, default=5,
                    help="slowest collectives to detail")
    args = ap.parse_args()
    if bool(args.trace) == bool(args.from_url):
        ap.error("give exactly one of a trace file or --from-url")
    if args.from_url:
        events, doc = fetch_url(args.from_url)
    else:
        events, doc = load_events(args.trace)
    out = analyze(events, top=args.top)
    pm = doc.get("horovod_postmortem") if isinstance(doc, dict) else None
    if pm:
        out["postmortem_verdict"] = pm.get("verdict")
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
