"""Per-op device-time profile of a train step via jax.profiler.trace.

Produces the bucket tables in docs/benchmarks.md: traces one scan
chunk of the requested model's train step on the real chip, then
aggregates the device lane of the Chrome trace by op family and prints
ms/step per bucket. Works over the tunneled device (the trace rides
the profiler plugin, not local hardware counters).

Usage:
    python scripts/profile_step.py                 # gpt2-small flash
    python scripts/profile_step.py --model resnet50 --batch 256
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import re
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.utils import chrome_trace  # noqa: E402


def capture(model: str, batch: int, seq: int, chunk: int, outdir: str):
    import jax

    from bench import _build, _make_scan_step

    kw = {}
    if model.startswith("gpt2"):
        kw = {"model_kw": {"attn_impl": "flash", "max_len": seq},
              "seq_len": seq}
    state, step_fn, inputs, labels, _, mesh = _build(
        model, 1, batch, **kw)
    scan_fn = _make_scan_step(step_fn, mesh, chunk)
    state, losses = scan_fn(state, inputs, labels)   # compile + warm
    jax.device_get(losses)
    with jax.profiler.trace(outdir):
        state, losses = scan_fn(state, inputs, labels)
        jax.device_get(losses)


def aggregate(outdir: str, steps: int):
    # Shared glob/gzip/parse helper (utils/chrome_trace) — one reader
    # for this script, engine/mesh_timeline.py and the tracing plane.
    events = chrome_trace.load_profiler_events(outdir)
    if events is None:
        raise RuntimeError(
            f"no Chrome trace captured under {outdir} — the profiler "
            "plugin produced nothing (capture failed or unsupported on "
            "this device transport)"
        )
    device_pids = {
        e["pid"] for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and "TPU" in e.get("args", {}).get("name", "")
    }
    buckets = collections.Counter()
    counts = collections.Counter()
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        n = e["name"]
        if n.startswith(("while", "jit_")) or not n.strip() \
                or n.isdigit():
            continue  # container frames double-count their children
        fam = ("attention_kernels" if re.match(r"attn[.\d]*$", n)
               else re.sub(r"[.\d]+$", "", n))
        buckets[fam] += e["dur"]
        counts[fam] += 1
    total = sum(buckets.values())
    rows = [
        {"bucket": k, "ms_per_step": round(v / steps / 1e3, 3),
         "ops_per_step": counts[k] // steps,
         "share_pct": round(100 * v / total, 1)}
        for k, v in buckets.most_common()
        if v / steps / 1e3 >= 0.01
    ]
    return {"total_ms_per_step": round(total / steps / 1e3, 2),
            "buckets": rows}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2-small")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--chunk", type=int, default=12)
    ap.add_argument("--keep-trace", action="store_true")
    args = ap.parse_args()

    outdir = tempfile.mkdtemp(prefix="hvdtpu_profile_")
    try:
        capture(args.model, args.batch, args.seq, args.chunk, outdir)
        result = aggregate(outdir, args.chunk)
        print(json.dumps(result, indent=1))
    finally:
        if args.keep_trace:
            print(f"trace kept at {outdir}", file=sys.stderr)
        else:
            shutil.rmtree(outdir, ignore_errors=True)


if __name__ == "__main__":
    main()
