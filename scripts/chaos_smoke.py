#!/usr/bin/env python
"""Chaos smoke: kill one of N local workers mid-step, by hand.

Reproduces the fault-tolerance acceptance scenario outside pytest
(tests/test_fault_tolerance.py::test_chaos_kill_one_of_four_workers):
spawn N process-mode workers allreducing in a loop, arm a deterministic
``kill:step=K`` fault-injection rule on one rank, and report how every
survivor died. Success means every survivor exited through
HorovodInternalError within 2x HOROVOD_TCP_TIMEOUT_SECONDS — no hang,
no raw ConnectionError.

    python scripts/chaos_smoke.py                 # 4 workers, kill rank 2 at step 3
    python scripts/chaos_smoke.py --np 8 --kill-rank 5 --kill-step 10
    python scripts/chaos_smoke.py --timeout 2.0 --steps 100
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import fault_injection
    from horovod_tpu.common.exceptions import HorovodInternalError

    STEPS = int(os.environ["CHAOS_STEPS"])
    hvd.init()
    rank = hvd.rank()
    try:
        for step in range(STEPS):
            hvd.allreduce(np.ones(8, np.float32), name="g")
            fault_injection.advance_step()
            if step % 10 == 0:
                print(f"rank {rank}: step {step}", flush=True)
        print(f"rank {rank}: completed all {STEPS} steps", flush=True)
        sys.exit(0)
    except HorovodInternalError as e:
        print(f"rank {rank}: HorovodInternalError: {e}", flush=True)
        sys.exit(42)
    except ConnectionError as e:
        print(f"rank {rank}: RAW ConnectionError LEAKED: {e}", flush=True)
        sys.exit(13)
""")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--np", dest="np_", type=int, default=4,
                    help="world size (default 4)")
    ap.add_argument("--kill-rank", type=int, default=2)
    ap.add_argument("--kill-step", type=int, default=3)
    ap.add_argument("--steps", type=int, default=50,
                    help="total training steps per worker")
    ap.add_argument("--timeout", type=float, default=5.0,
                    help="HOROVOD_TCP_TIMEOUT_SECONDS for the workers")
    args = ap.parse_args()

    from horovod_tpu.runner.hosts import get_host_assignments, parse_hosts
    from horovod_tpu.runner.launch import slot_env
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    server = RendezvousServer()
    port = server.start()
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "worker.py")
        with open(script, "w") as f:
            f.write(WORKER)

        slots = get_host_assignments(
            parse_hosts(f"localhost:{args.np_}"), args.np_)
        procs = {}
        try:
            for slot in slots:
                env = dict(os.environ)
                env.update(slot_env(slot, "127.0.0.1", port))
                env["PYTHONPATH"] = REPO
                env["HVDRUN_FORCE_LOCAL"] = "1"
                env["HOROVOD_CYCLE_TIME"] = "1"
                env["HOROVOD_TCP_TIMEOUT_SECONDS"] = str(args.timeout)
                env["CHAOS_STEPS"] = str(args.steps)
                env.pop("HOROVOD_FAULT_INJECT", None)
                if slot.rank == args.kill_rank:
                    env["HOROVOD_FAULT_INJECT"] = f"kill:step={args.kill_step}"
                procs[slot.rank] = subprocess.Popen(
                    [sys.executable, script], env=env)
            print(f"spawned {args.np_} workers; rank {args.kill_rank} dies "
                  f"at step {args.kill_step} "
                  f"(timeout={args.timeout}s)", flush=True)

            t_death = None
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                if procs[args.kill_rank].poll() is not None:
                    t_death = time.monotonic()
                    break
                time.sleep(0.1)
            if t_death is None:
                print("FAIL: doomed worker never died", flush=True)
                return 2
            print(f"rank {args.kill_rank} died "
                  f"(exit {procs[args.kill_rank].returncode})", flush=True)

            budget = 2 * args.timeout + 30
            ok = True
            for rank, proc in sorted(procs.items()):
                if rank == args.kill_rank:
                    continue
                remaining = budget - (time.monotonic() - t_death)
                try:
                    proc.wait(timeout=max(remaining, 1.0))
                except subprocess.TimeoutExpired:
                    print(f"FAIL: rank {rank} HUNG past {budget:.0f}s",
                          flush=True)
                    ok = False
                    continue
                verdict = {42: "clean HorovodInternalError",
                           0: "completed (died pre-mesh?)",
                           13: "RAW ConnectionError (FORBIDDEN)"}.get(
                               proc.returncode, "unexpected")
                print(f"rank {rank}: exit {proc.returncode} ({verdict})",
                      flush=True)
                ok = ok and proc.returncode == 42
            print("PASS" if ok else "FAIL", flush=True)
            return 0 if ok else 1
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
            server.stop()


if __name__ == "__main__":
    sys.exit(main())
