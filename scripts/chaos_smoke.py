#!/usr/bin/env python
"""Chaos smoke: kill — or wedge — one of N local workers mid-step.

Reproduces the fault-tolerance acceptance scenarios outside pytest:
spawn N process-mode workers allreducing in a loop, arm a deterministic
fault-injection rule on one rank, and report how every survivor died.

Default (kill) mode — tests/test_fault_tolerance.py's scenario: the
doomed rank ``os._exit``\\s at a step; success means every survivor
exited through HorovodInternalError within 2x
``HOROVOD_TCP_TIMEOUT_SECONDS`` — no hang, no raw ConnectionError.

``--wedge`` mode — tests/test_health.py's scenario: the doomed rank
FREEZES (process alive, sockets open, heartbeats stop) with
``HOROVOD_TCP_TIMEOUT_SECONDS=0`` (unbounded), the hang only the
liveness plane can bound. Success means every survivor raised
HorovodInternalError NAMING the wedged rank within
``miss_limit x interval`` (+ slack), while the wedged process itself
stayed alive until this script killed it.

``--killall`` mode — whole-job loss, the scenario the elastic plane
alone cannot survive and the durability plane (docs/checkpoint.md)
exists for: EVERY rank dies at the kill step (rendezvous server
included), then a fresh job over the same checkpoint dir must resume
at the last committed checkpoint with bitwise state parity. Delegates
to ``checkpoint_smoke``'s two-phase harness.

``--serving`` mode — the serving plane's wedge scenario
(docs/serving.md): a 4-rank continuous-batching serving mesh under
concurrent HTTP load has one replica wedged mid-traffic; the liveness
verdict evicts it, survivors re-mesh and every accepted request still
completes. Delegates to ``serving_smoke``'s harness (its phase 3).
Add ``--killdoor N`` to instead hard-kill the ACTIVE front door of a
two-door fleet after N admissions (serving_smoke phases 4-5): the
standby door must win the failover election with zero accepted-request
loss.

    python scripts/chaos_smoke.py                 # 4 workers, kill rank 2 at step 3
    python scripts/chaos_smoke.py --np 8 --kill-rank 5 --kill-step 10
    python scripts/chaos_smoke.py --wedge         # wedge rank 2 instead
    python scripts/chaos_smoke.py --wedge --hb-interval 0.5 --hb-miss 4
    python scripts/chaos_smoke.py --killall --kill-step 7
    python scripts/chaos_smoke.py --serving       # wedge a serving replica
    python scripts/chaos_smoke.py --serving --killdoor 5  # kill the active door
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import fault_injection
    from horovod_tpu.common.exceptions import HorovodInternalError

    STEPS = int(os.environ["CHAOS_STEPS"])
    VERDICT = os.environ.get("CHAOS_VERDICT_FILE")

    def verdict(text):
        if VERDICT:
            with open(VERDICT, "w") as f:
                f.write(text)

    hvd.init()
    rank = hvd.rank()
    try:
        for step in range(STEPS):
            hvd.allreduce(np.ones(8, np.float32), name="g")
            fault_injection.advance_step()
            if step % 10 == 0:
                print(f"rank {rank}: step {step}", flush=True)
        print(f"rank {rank}: completed all {STEPS} steps", flush=True)
        verdict("completed")
        sys.exit(0)
    except HorovodInternalError as e:
        print(f"rank {rank}: HorovodInternalError: {e}", flush=True)
        verdict(str(e))
        sys.exit(42)
    except ConnectionError as e:
        print(f"rank {rank}: RAW ConnectionError LEAKED: {e}", flush=True)
        verdict(f"RAW: {e}")
        sys.exit(13)
""")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--np", dest="np_", type=int, default=4,
                    help="world size (default 4)")
    ap.add_argument("--kill-rank", type=int, default=2,
                    help="rank to kill/wedge (default 2)")
    ap.add_argument("--kill-step", type=int, default=3)
    ap.add_argument("--steps", type=int, default=50,
                    help="total training steps per worker")
    ap.add_argument("--timeout", type=float, default=5.0,
                    help="HOROVOD_TCP_TIMEOUT_SECONDS for kill mode")
    ap.add_argument("--wedge", action="store_true",
                    help="wedge (freeze) the doomed rank instead of "
                         "killing it, with unbounded TCP timeouts — "
                         "exercises heartbeat detection")
    ap.add_argument("--hb-interval", type=float, default=0.5,
                    help="HOROVOD_HEARTBEAT_INTERVAL_SECONDS (wedge mode)")
    ap.add_argument("--hb-miss", type=int, default=4,
                    help="HOROVOD_HEARTBEAT_MISS_LIMIT (wedge mode)")
    ap.add_argument("--killall", action="store_true",
                    help="kill EVERY rank at --kill-step (whole-job "
                         "loss) and assert a restarted job resumes "
                         "from the last committed durable checkpoint "
                         "with bitwise parity")
    ap.add_argument("--serving", action="store_true",
                    help="wedge one replica of a 4-rank serving mesh "
                         "under concurrent HTTP load; the verdict "
                         "evicts it and every accepted request still "
                         "completes (docs/serving.md)")
    ap.add_argument("--killdoor", type=int, default=None, metavar="N",
                    help="with --serving: run ONLY the fleet phases — "
                         "a killdoor:after=N chaos rule hard-kills the "
                         "ACTIVE front door after N admissions; the "
                         "standby door must win the election with zero "
                         "accepted-request loss (docs/serving.md "
                         "\"Failure drills\")")
    ap.add_argument("--interval", type=int, default=2,
                    help="HOROVOD_CHECKPOINT_INTERVAL_STEPS "
                         "(killall mode)")
    ap.add_argument("--transport", choices=["tcp", "shm"], default="tcp",
                    help="shm: data-plane frames between the co-located "
                         "workers ride the shared-memory overlay "
                         "(HOROVOD_TRANSPORT=auto) while heartbeats "
                         "stay on TCP — proves kill/wedge detection "
                         "and root-cause attribution hold when the "
                         "dead peer is reached over shared memory")
    args = ap.parse_args()

    if args.killall:
        return run_killall(args)
    if args.serving:
        return run_serving(args)

    from horovod_tpu.runner.hosts import get_host_assignments, parse_hosts
    from horovod_tpu.runner.launch import slot_env
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    server = RendezvousServer()
    port = server.start()
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "worker.py")
        with open(script, "w") as f:
            f.write(WORKER)

        slots = get_host_assignments(
            parse_hosts(f"localhost:{args.np_}"), args.np_)
        procs = {}
        verdict_files = {}
        try:
            for slot in slots:
                env = dict(os.environ)
                env.update(slot_env(slot, "127.0.0.1", port))
                env["PYTHONPATH"] = REPO
                env["HVDRUN_FORCE_LOCAL"] = "1"
                env["HOROVOD_CYCLE_TIME"] = "1"
                env["CHAOS_STEPS"] = str(args.steps)
                verdict_files[slot.rank] = os.path.join(
                    td, f"verdict_{slot.rank}")
                env["CHAOS_VERDICT_FILE"] = verdict_files[slot.rank]
                env.pop("HOROVOD_FAULT_INJECT", None)
                if args.transport == "shm":
                    env["HOROVOD_TRANSPORT"] = "auto"
                if args.wedge:
                    # The headline scenario: UNBOUNDED socket I/O — only
                    # the liveness plane bounds detection.
                    env["HOROVOD_TCP_TIMEOUT_SECONDS"] = "0"
                    env["HOROVOD_HEARTBEAT_INTERVAL_SECONDS"] = str(
                        args.hb_interval)
                    env["HOROVOD_HEARTBEAT_MISS_LIMIT"] = str(args.hb_miss)
                else:
                    env["HOROVOD_TCP_TIMEOUT_SECONDS"] = str(args.timeout)
                if slot.rank == args.kill_rank:
                    action = "wedge" if args.wedge else "kill"
                    env["HOROVOD_FAULT_INJECT"] = \
                        f"{action}:step={args.kill_step}"
                procs[slot.rank] = subprocess.Popen(
                    [sys.executable, script], env=env)
            mode = "wedges" if args.wedge else "dies"
            print(f"spawned {args.np_} workers; rank {args.kill_rank} "
                  f"{mode} at step {args.kill_step}", flush=True)

            if args.wedge:
                return run_wedge(args, procs, verdict_files)
            return run_kill(args, procs)
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
            server.stop()


def run_killall(args) -> int:
    """Whole-job loss + recovery. The kill rule is armed on EVERY rank
    (``kill:step=K`` with no rank= filter), so nothing survives — not
    even the rendezvous KV. checkpoint_smoke's harness then restarts
    the job from nothing but the shared checkpoint dir and asserts a
    bitwise resume at the last committed step, bitwise-identical final
    weights, and zero partial-checkpoint debris."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import checkpoint_smoke

    if args.kill_step <= args.interval:
        print(f"FAIL: --kill-step {args.kill_step} <= --interval "
              f"{args.interval}: no checkpoint can commit before the "
              "kill", flush=True)
        return 2
    return checkpoint_smoke.run_killall(args)


def run_serving(args) -> int:
    """Serving-plane chaos: delegate to serving_smoke's harness with
    the same wedge knobs this script uses (docs/serving.md). With
    --killdoor N only the fleet phases run: the active front door is
    hard-killed after N admissions and the standby door must take over
    with zero accepted-request loss."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import serving_smoke

    sys.argv = ["serving_smoke",
                "--np", str(args.np_),
                "--wedge-rank", str(args.kill_rank),
                "--hb-interval", str(args.hb_interval),
                "--hb-miss", str(args.hb_miss)]
    if args.killdoor is not None:
        sys.argv += ["--fleet-only", "--killdoor-after",
                     str(args.killdoor)]
    return serving_smoke.main()


def run_kill(args, procs) -> int:
    t_death = None
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if procs[args.kill_rank].poll() is not None:
            t_death = time.monotonic()
            break
        time.sleep(0.1)
    if t_death is None:
        print("FAIL: doomed worker never died", flush=True)
        return 2
    print(f"rank {args.kill_rank} died "
          f"(exit {procs[args.kill_rank].returncode})", flush=True)

    budget = 2 * args.timeout + 30
    ok = True
    for rank, proc in sorted(procs.items()):
        if rank == args.kill_rank:
            continue
        remaining = budget - (time.monotonic() - t_death)
        try:
            proc.wait(timeout=max(remaining, 1.0))
        except subprocess.TimeoutExpired:
            print(f"FAIL: rank {rank} HUNG past {budget:.0f}s",
                  flush=True)
            ok = False
            continue
        verdict = {42: "clean HorovodInternalError",
                   0: "completed (died pre-mesh?)",
                   13: "RAW ConnectionError (FORBIDDEN)"}.get(
                       proc.returncode, "unexpected")
        print(f"rank {rank}: exit {proc.returncode} ({verdict})",
              flush=True)
        ok = ok and proc.returncode == 42
    print("PASS" if ok else "FAIL", flush=True)
    return 0 if ok else 1


def run_wedge(args, procs, verdict_files) -> int:
    window = args.hb_interval * args.hb_miss
    # Survivors must fail within the detection window (+ generous slack
    # for oversubscribed CI boxes); the wedged process must stay ALIVE.
    budget = window + 60
    deadline = time.monotonic() + 120 + budget
    ok = True
    rows = []
    for rank, proc in sorted(procs.items()):
        if rank == args.kill_rank:
            continue
        try:
            proc.wait(timeout=max(deadline - time.monotonic(), 1.0))
        except subprocess.TimeoutExpired:
            rows.append((rank, "HUNG", "survivor hung past the "
                         "heartbeat window (liveness plane broken)"))
            ok = False
            continue
        msg = ""
        try:
            with open(verdict_files[rank]) as f:
                msg = f.read()
        except OSError:
            pass
        named = f"rank {args.kill_rank}" in msg and "declared dead" in msg
        clean = proc.returncode == 42
        rows.append((rank, f"exit {proc.returncode}",
                     msg if msg else "(no verdict written)"))
        ok = ok and clean and named
    if procs[args.kill_rank].poll() is not None:
        print(f"FAIL: wedged rank {args.kill_rank} DIED "
              f"(exit {procs[args.kill_rank].returncode}) — a wedge must "
              "keep the process alive", flush=True)
        ok = False
    else:
        print(f"wedged rank {args.kill_rank} is alive and frozen, as "
              "intended (killing it now)", flush=True)

    print(f"\nper-rank verdicts (window {window:.1f}s = "
          f"{args.hb_miss} x {args.hb_interval:g}s):", flush=True)
    for rank, status, msg in rows:
        print(f"  rank {rank}: {status}: {msg}", flush=True)
    print("PASS" if ok else "FAIL", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
