#!/usr/bin/env python
"""hvdtop: the live fleet operator console (docs/events.md).

One terminal, the whole job: polls a rank's metrics endpoint
(``/status``, ``/goodput``, ``/alerts``, ``/events``) and — when a
rendezvous server is reachable — the elastic control plane's KV rows
(``meta/epoch``, ``controller/last``, ``capacity/grant``, the current
epoch's drain marker), then renders:

* header — world size, topology epoch, uptime, checkpoint step;
* per-rank goodput table — steps, goodput ratio, exposed-comm badput
  (the fleet fold at /goodput names the straggler);
* firing alerts, fleet-wide (rank-attributed);
* a native-core badge — whether this rank's data plane runs the
  GIL-free C++ kernels or the numpy fallback (docs/native.md);
* the elasticity controller's last decision and any capacity grant —
  the ROADMAP item 5 operator surface for ``controller/last``;
* an in-flight drain notice for the current epoch;
* the serving fleet — the polled rank's ``/serving`` view, the KV door
  row (active door, election epoch, door set, membership) and the
  serving autoscaler's last decision (``serving``/``scale``);
* the chronicle tail — the newest causally-ordered lifecycle events
  from the /events fleet fold (epoch, step cursor, rank, kind).

Usage:

    python scripts/hvdtop.py --metrics 127.0.0.1:9911
    python scripts/hvdtop.py --metrics :9911 --rendezvous 127.0.0.1:7007
    python scripts/hvdtop.py --metrics :9911 --once   # one frame, no TUI

``--once`` prints a single frame and exits (CI smokes drive it this
way); otherwise the console clears and redraws every ``--interval``
seconds until Ctrl-C. Everything degrades: an endpoint that is down
renders as "unreachable", never a crash — an operator opens hvdtop
precisely when the job is misbehaving.
"""
from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import time
from typing import Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


# -- collection ---------------------------------------------------------
def fetch_json(host: str, port: int, path: str,
               timeout: float = 5.0) -> Optional[dict]:
    """One GET against the metrics endpoint; None when unreachable."""
    try:
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            if resp.status != 200:
                return None
            return json.loads(resp.read())
        finally:
            conn.close()
    except Exception:
        return None


def _kv_json(kv, scope: str, key: str) -> Optional[dict]:
    try:
        raw = kv.get(scope, key)
        return json.loads(raw.decode()) if raw else None
    except Exception:
        return None


def gather(host: str, port: int, kv=None) -> dict:
    """One polling round: every section the renderer needs, with None
    for whatever was unreachable. Pure data — tests call this (or feed
    `render` synthetic snapshots) without a terminal."""
    snap = {
        "wall": time.time(),
        "status": fetch_json(host, port, "/status"),
        "goodput": fetch_json(host, port, "/goodput"),
        "alerts": fetch_json(host, port, "/alerts"),
        "events": fetch_json(host, port, "/events"),
        "serving": fetch_json(host, port, "/serving"),
        "controller": None,
        "grant": None,
        "drain": None,
        "kv_epoch": None,
        "serving_door": None,
        "serving_scale": None,
        "serving_load": None,
    }
    if kv is not None:
        snap["controller"] = _kv_json(kv, "controller", "last")
        snap["serving_door"] = _kv_json(kv, "serving", "door")
        snap["serving_scale"] = _kv_json(kv, "serving", "scale")
        snap["serving_load"] = _kv_json(kv, "serving", "load")
        try:
            raw = kv.get("capacity", "grant")
            snap["grant"] = int(raw.decode()) if raw else None
        except Exception:
            pass
        epoch = None
        try:
            raw = kv.get("meta", "epoch")
            epoch = int(raw.decode()) if raw else None
        except Exception:
            pass
        snap["kv_epoch"] = epoch
        if epoch is not None:
            snap["drain"] = _kv_json(kv, f"drain_e{epoch}", "any")
    return snap


# -- rendering ----------------------------------------------------------
def _age(wall: Optional[float], now: float) -> str:
    if not wall:
        return "?"
    d = max(now - wall, 0.0)
    return f"{d:.0f}s ago" if d < 120 else f"{d / 60:.0f}m ago"


def _fmt_ratio(r) -> str:
    return f"{r:.3f}" if isinstance(r, (int, float)) else "-"


def render(snap: dict, events_tail: int = 12) -> str:
    """A full frame as text (testable; `main` only adds the ANSI
    clear)."""
    now = snap.get("wall", time.time())
    lines = []
    st = snap.get("status")
    if st is None:
        lines.append("hvdtop — metrics endpoint unreachable")
    else:
        ck = st.get("checkpoint") or {}
        gp = st.get("goodput") or {}
        epoch = snap.get("kv_epoch")
        lines.append(
            "hvdtop — world {w}  epoch {e}  step {s}  "
            "last commit {c}".format(
                w=st.get("size", "?"),
                e="-" if epoch is None else epoch,
                s=gp.get("steps", "-"),
                c=ck.get("last_committed_step", "-")))
    lines.append("=" * 72)

    # Per-rank goodput (the fleet fold names the straggler).
    gp = snap.get("goodput") or {}
    fleet = (gp.get("fleet") or {}).get("ranks") or {}
    if fleet:
        lines.append("rank  steps  goodput  exposed_comm_s")
        worst = str((gp.get("fleet") or {}).get("max_exposed_comm_rank"))
        for r in sorted(fleet, key=lambda x: int(x)):
            row = fleet[r]
            mark = "  <- max exposed" if r == worst else ""
            lines.append(
                f"{r:>4}  {row.get('steps', '-'):>5}  "
                f"{_fmt_ratio(row.get('goodput_ratio')):>7}  "
                f"{row.get('exposed_comm_seconds', 0.0):>14.2f}{mark}")
    elif gp.get("local"):
        loc = gp["local"]
        lines.append(
            "local goodput: steps {s}  ratio {r}".format(
                s=(loc.get("steps") or {}).get("total", "-"),
                r=_fmt_ratio((loc.get("goodput") or {}).get("ratio"))))
    else:
        lines.append("goodput: unreachable")

    # Alerts (fleet first; fall back to local).
    al = snap.get("alerts") or {}
    firing = []
    by_rule = (al.get("fleet") or {}).get("firing_by_rule") or {}
    for rule, ranks in by_rule.items():
        firing.append(f"{rule} (ranks {ranks})")
    if not firing:
        firing = ["local: " + (f.get("rule", "?") if isinstance(f, dict)
                               else str(f))
                  for f in (al.get("local") or {}).get("firing") or []]
    lines.append("-" * 72)
    if firing:
        lines.append("ALERTS FIRING: " + "; ".join(sorted(firing)))
    else:
        lines.append("alerts: none firing")

    # Native-core badge (docs/native.md): one line, operator truth
    # about which data plane the rank runs.
    nat = (st or {}).get("native")
    if nat:
        ks = nat.get("kernels") or {}
        active = sum(1 for v in ks.values() if v)
        if nat.get("loaded"):
            lines.append(
                "native: on  abi {abi}  threads {th}  kernels "
                "{a}/{n} active".format(
                    abi=nat.get("abi", "?"), th=nat.get("threads", "?"),
                    a=active, n=len(ks)))
        else:
            why = ("disabled" if nat.get("disabled")
                   else ("built, load failed" if nat.get("built")
                         else "not built"))
            lines.append(f"native: fallback (numpy) — {why}")

    # ZeRO badge (docs/running.md "ZeRO sharded optimizer state"):
    # how much optimizer-state memory this rank actually holds vs a
    # full replica — the number the mode exists to shrink.
    zr = (st or {}).get("zero")
    if zr and zr.get("enabled"):
        sh = zr.get("sharded_state_bytes")
        rp = zr.get("replicated_state_bytes")
        saving = (f"  ({rp / sh:.1f}x saving)"
                  if sh and rp and sh > 0 else "")
        ef = "  ef on" if zr.get("error_feedback") else ""
        lines.append(
            "zero: stage {s} {pl}  world {w}  state {sh}/{rp} B{sv}{ef}"
            .format(s=zr.get("stage", "?"), pl=zr.get("plane", "?"),
                    w=zr.get("world", "?"), sh=sh, rp=rp, sv=saving,
                    ef=ef))

    # Controller decision + capacity grant (ROADMAP item 5 surface).
    ctl = snap.get("controller")
    if ctl:
        lines.append(
            "controller: {a}  np {c} -> {t}  ({reason})  [{age}]".format(
                a=ctl.get("action", "?"), c=ctl.get("current_np", "?"),
                t=ctl.get("target_np", "?"),
                reason=ctl.get("reason", ""),
                age=_age(ctl.get("wall"), now)))
    else:
        lines.append("controller: no decision published")
    if snap.get("grant") is not None:
        lines.append(f"capacity grant: {snap['grant']} slots")
    drain = snap.get("drain")
    if drain:
        lines.append(
            "DRAIN in flight: phase {p}  [{age}]".format(
                p=drain.get("phase", "?"),
                age=_age(drain.get("wall"), now)))

    # Serving fleet (docs/serving.md): the polled rank's /serving view,
    # the KV door row (active door + election epoch) and the serving
    # autoscaler's last decision — same shape as the controller line.
    sv = snap.get("serving")
    door = snap.get("serving_door")
    if sv or door:
        lines.append("-" * 72)
    if sv:
        fe = sv.get("frontend") or {}
        lines.append(
            "serving: {role}  world {w}  weights step {ws}  "
            "queue {q}  inflight {i}".format(
                role=sv.get("role", "?"), w=sv.get("world", "?"),
                ws=sv.get("weight_step", "?"),
                q=fe.get("queue_depth", "-"),
                i=fe.get("inflight", "-")))
    if door:
        lines.append(
            "doors: active r{d}  epoch {e}  doors {ds}  members {m}"
            "{stopped}  [{age}]".format(
                d=door.get("door", "?"), e=door.get("epoch", "?"),
                ds=door.get("doors", []), m=door.get("members", []),
                stopped="  STOPPED" if door.get("stopped") else "",
                age=_age(door.get("wall"), now)))
    sc = snap.get("serving_scale")
    if sc:
        lines.append(
            "serving autoscaler: {a}  replicas {c} -> {t}  backlog "
            "{b:.0f}  ({reason})  [{age}]".format(
                a=sc.get("action", "?"), c=sc.get("replicas", "?"),
                t=sc.get("target", "?"),
                b=float(sc.get("backlog", 0.0)),
                reason=sc.get("reason", ""),
                age=_age(sc.get("wall"), now)))

    # Chronicle tail: fleet fold when the coordinator serves it,
    # local ring otherwise.
    ev = snap.get("events") or {}
    rows = (ev.get("fleet") or {}).get("events") \
        or (ev.get("local") or {}).get("events") or []
    lines.append("-" * 72)
    lines.append(f"chronicle (newest {min(len(rows), events_tail)} of "
                 f"{len(rows)} lifecycle events):")
    for d in rows[-events_tail:]:
        attrs = d.get("attrs") or {}
        extras = " ".join(f"{k}={attrs[k]}" for k in sorted(attrs))
        lines.append(
            "  e{epoch:<3} step {step:<6} r{rank:<3} {sev:<5} "
            "{kind:<22} {extras}".format(
                epoch=d.get("epoch", -1), step=d.get("step", 0),
                rank=d.get("rank", "?"), sev=d.get("sev", ""),
                kind=d.get("kind", "?"), extras=extras).rstrip())
    if not rows:
        lines.append("  (events plane disabled or empty)")
    return "\n".join(lines)


# -- entry point --------------------------------------------------------
def _parse_hostport(s: str, default_host: str = "127.0.0.1"):
    host, _, port = s.rpartition(":")
    return host or default_host, int(port)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--metrics", required=True,
                   help="host:port of a rank's metrics endpoint "
                        "(HOROVOD_METRICS_PORT); ':9911' = localhost")
    p.add_argument("--rendezvous", default=None,
                   help="host:port of the rendezvous server (defaults "
                        "to HOROVOD_RENDEZVOUS_ADDR/PORT when set)")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--events", type=int, default=12,
                   help="chronicle tail length")
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit (no screen clearing)")
    args = p.parse_args(argv)

    host, port = _parse_hostport(args.metrics)
    kv = None
    rdv = args.rendezvous
    if rdv is None:
        from horovod_tpu.utils import env as env_cfg

        addr = env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR)
        kv_port = env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0)
        if addr and kv_port:
            rdv = f"{addr}:{kv_port}"
    if rdv:
        from horovod_tpu.backend.rendezvous import RendezvousClient

        rhost, rport = _parse_hostport(rdv)
        kv = RendezvousClient(rhost, rport)

    while True:
        frame = render(gather(host, port, kv), events_tail=args.events)
        if args.once:
            print(frame)
            return 0
        # Home + clear-to-end: redraw in place without scrollback spam.
        sys.stdout.write("\x1b[H\x1b[2J" + frame + "\n")
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
