#!/usr/bin/env python
"""Data-plane perf smoke: a real 2-worker loopback run over every ring
schedule, asserting completion and EXACT byte accounting — no flaky
throughput thresholds (CI boxes are too noisy for those; the numbers
live in examples/microbench_allreduce.py and BENCH runs instead).

What it pins down:

* the zero-copy TCP data plane (sendmsg scatter-gather sends,
  recv_into receives, persistent peer senders) completes star,
  single-shot ring and segmented pipelined ring allreduces with
  correct results;
* `horovod_allreduce_bytes_total` accounts every enqueued payload byte
  exactly (iters x nbytes per rank) — the engine counts negotiated
  input bytes, so the number is deterministic regardless of which
  algorithm moved them;
* the new transport counters moved: `horovod_tcp_sendmsg_frames_total`
  > 0 on every rank and `horovod_ring_segments_total` > 0 wherever a
  ring schedule ran (and the segmented run produced strictly more
  segments than chunks);
* a 2-channel pipelined window (ring bigs on the bulk lane, star
  smalls on the latency lane, fusion off) still accounts every byte
  exactly and moves frames on channel tags 0, 1 and ctrl
  (`horovod_tcp_channel_frames_total`).

Run by scripts/ci.sh; also a manual repro tool:

    python scripts/perf_smoke.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

ITERS = 4
COUNT = 1 << 16  # 256KB float32 — above the default ring threshold


def worker():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    n = hvd.size()
    expect_bytes = 0
    schedules = [
        ("star", {"HOROVOD_CPU_OPERATIONS": "star"}),
        ("ring", {"HOROVOD_RING_THRESHOLD": "0",
                  "HOROVOD_RING_SEGMENT_BYTES": "0"}),
        # 64KB segments over a 64KB chunk (np=2) -> >1 segment/chunk.
        ("segring", {"HOROVOD_RING_THRESHOLD": "0",
                     "HOROVOD_RING_SEGMENT_BYTES": str(1 << 16)}),
    ]
    seg_counts = {}
    for name, env in schedules:
        os.environ.pop("HOROVOD_CPU_OPERATIONS", None)
        os.environ.update(env)
        before = hvd.metrics()["metrics"].get(
            "horovod_ring_segments_total", 0)
        for i in range(ITERS):
            x = np.full(COUNT, float(hvd.rank() + 1), np.float32)
            out = np.asarray(hvd.allreduce(
                x, name=f"perf.{name}.{i}", op=hvd.Sum))
            assert out.shape == (COUNT,), out.shape
            assert float(out[0]) == sum(range(1, n + 1)), (name, out[0])
            expect_bytes += x.nbytes
        seg_counts[name] = (hvd.metrics()["metrics"].get(
            "horovod_ring_segments_total", 0) - before)

    # 2-channel pipelined run: an async window of ring bigs (bulk lane)
    # + star smalls (latency lane), fusion off so every op is its own
    # response. Byte accounting must stay EXACT with two channels in
    # flight, and the channel-tagged frame counters must show traffic on
    # both data lanes plus the control lane.
    from horovod_tpu.common import basics

    eng = basics.engine()
    prev_fusion = eng.controller.fusion_threshold
    eng.controller.fusion_threshold = 1
    os.environ.update({"HOROVOD_RING_THRESHOLD": "0",
                       "HOROVOD_RING_SEGMENT_BYTES": str(1 << 16),
                       "HOROVOD_NUM_CHANNELS": "2"})
    handles = []
    for i in range(ITERS):
        big = np.full(COUNT, float(hvd.rank() + 1), np.float32)
        small = np.full(1024, float(hvd.rank() + 1), np.float32)
        handles.append((eng.enqueue_allreduce(big, name=f"pc.big.{i}"),
                        COUNT, big.nbytes))
        handles.append((eng.enqueue_allreduce(small, name=f"pc.small.{i}"),
                        1024, small.nbytes))
        expect_bytes += big.nbytes + small.nbytes
    for h, count, _ in handles:
        out = np.asarray(eng.synchronize(h, timeout=120))
        assert out.shape == (count,), out.shape
        assert float(out[0]) == sum(range(1, n + 1)), out[0]
    hvd.barrier()
    eng.controller.fusion_threshold = prev_fusion

    snap = hvd.metrics()["metrics"]
    got = snap["horovod_allreduce_bytes_total"]
    assert got == expect_bytes, (
        f"allreduce_bytes_total accounting drifted: got {got}, "
        f"expected exactly {expect_bytes}")
    assert snap.get("horovod_tcp_sendmsg_frames_total", 0) > 0, snap
    # Channel-tag counters: bulk lane 0 (ring bigs), latency lane 1
    # (star smalls), and the control plane all moved frames.
    for label in ("0", "1", "ctrl"):
        key = f'horovod_tcp_channel_frames_total{{channel="{label}"}}'
        assert snap.get(key, 0) > 0, (label, sorted(
            k for k in snap if "channel_frames" in k))
    # Ring chunks: n per allreduce move as >=1 segment each on the send
    # side; the 64KB-segment run must split chunks further.
    assert seg_counts["star"] == 0, seg_counts
    assert seg_counts["ring"] >= ITERS, seg_counts
    assert seg_counts["segring"] > seg_counts["ring"], seg_counts
    checks = {"rank": hvd.rank(), "bytes": got, "segments": seg_counts}
    hvd.shutdown()
    return checks


def main():
    from horovod_tpu.runner import run

    results = run(worker, np=2, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_TCP_TIMEOUT_SECONDS": "60",
    })
    assert len(results) == 2, results
    assert all(r["bytes"] == results[0]["bytes"] for r in results), results
    print("perf smoke OK:", results)


if __name__ == "__main__":
    main()
