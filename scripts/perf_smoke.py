#!/usr/bin/env python
"""Data-plane perf smoke: a real 2-worker loopback run over every ring
schedule, asserting completion and EXACT byte accounting — no flaky
throughput thresholds (CI boxes are too noisy for those; the numbers
live in examples/microbench_allreduce.py and BENCH runs instead).

What it pins down:

* the zero-copy TCP data plane (sendmsg scatter-gather sends,
  recv_into receives, persistent peer senders) completes star,
  single-shot ring and segmented pipelined ring allreduces with
  correct results;
* `horovod_allreduce_bytes_total` accounts every enqueued payload byte
  exactly (iters x nbytes per rank) — the engine counts negotiated
  input bytes, so the number is deterministic regardless of which
  algorithm moved them;
* the new transport counters moved: `horovod_tcp_sendmsg_frames_total`
  > 0 on every rank and `horovod_ring_segments_total` > 0 wherever a
  ring schedule ran (and the segmented run produced strictly more
  segments than chunks);
* a 2-channel pipelined window (ring bigs on the bulk lane, star
  smalls on the latency lane, fusion off) still accounts every byte
  exactly and moves frames on channel tags 0, 1 and ctrl
  (`horovod_tcp_channel_frames_total`).

Run by scripts/ci.sh; also a manual repro tool:

    python scripts/perf_smoke.py        # the data-plane legs
    python scripts/perf_smoke.py zero   # np=4 ZeRO two-leg accounting
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

ITERS = 4
COUNT = 1 << 16  # 256KB float32 — above the default ring threshold


def worker():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    n = hvd.size()
    expect_bytes = 0
    schedules = [
        ("star", {"HOROVOD_CPU_OPERATIONS": "star"}),
        ("ring", {"HOROVOD_RING_THRESHOLD": "0",
                  "HOROVOD_RING_SEGMENT_BYTES": "0"}),
        # 64KB segments over a 64KB chunk (np=2) -> >1 segment/chunk.
        ("segring", {"HOROVOD_RING_THRESHOLD": "0",
                     "HOROVOD_RING_SEGMENT_BYTES": str(1 << 16)}),
    ]
    seg_counts = {}
    for name, env in schedules:
        os.environ.pop("HOROVOD_CPU_OPERATIONS", None)
        os.environ.update(env)
        before = hvd.metrics()["metrics"].get(
            "horovod_ring_segments_total", 0)
        for i in range(ITERS):
            x = np.full(COUNT, float(hvd.rank() + 1), np.float32)
            out = np.asarray(hvd.allreduce(
                x, name=f"perf.{name}.{i}", op=hvd.Sum))
            assert out.shape == (COUNT,), out.shape
            assert float(out[0]) == sum(range(1, n + 1)), (name, out[0])
            expect_bytes += x.nbytes
        seg_counts[name] = (hvd.metrics()["metrics"].get(
            "horovod_ring_segments_total", 0) - before)

    # 2-channel pipelined run: an async window of ring bigs (bulk lane)
    # + star smalls (latency lane), fusion off so every op is its own
    # response. Byte accounting must stay EXACT with two channels in
    # flight, and the channel-tagged frame counters must show traffic on
    # both data lanes plus the control lane.
    from horovod_tpu.common import basics

    eng = basics.engine()
    prev_fusion = eng.controller.fusion_threshold
    eng.controller.fusion_threshold = 1
    os.environ.update({"HOROVOD_RING_THRESHOLD": "0",
                       "HOROVOD_RING_SEGMENT_BYTES": str(1 << 16),
                       "HOROVOD_NUM_CHANNELS": "2"})
    handles = []
    for i in range(ITERS):
        big = np.full(COUNT, float(hvd.rank() + 1), np.float32)
        small = np.full(1024, float(hvd.rank() + 1), np.float32)
        handles.append((eng.enqueue_allreduce(big, name=f"pc.big.{i}"),
                        COUNT, big.nbytes))
        handles.append((eng.enqueue_allreduce(small, name=f"pc.small.{i}"),
                        1024, small.nbytes))
        expect_bytes += big.nbytes + small.nbytes
    for h, count, _ in handles:
        out = np.asarray(eng.synchronize(h, timeout=120))
        assert out.shape == (count,), out.shape
        assert float(out[0]) == sum(range(1, n + 1)), out[0]
    hvd.barrier()
    eng.controller.fusion_threshold = prev_fusion

    snap = hvd.metrics()["metrics"]
    got = snap["horovod_allreduce_bytes_total"]
    assert got == expect_bytes, (
        f"allreduce_bytes_total accounting drifted: got {got}, "
        f"expected exactly {expect_bytes}")
    assert snap.get("horovod_tcp_sendmsg_frames_total", 0) > 0, snap
    # Channel-tag counters: bulk lane 0 (ring bigs), latency lane 1
    # (star smalls), and the control plane all moved frames.
    for label in ("0", "1", "ctrl"):
        key = f'horovod_tcp_channel_frames_total{{channel="{label}"}}'
        assert snap.get(key, 0) > 0, (label, sorted(
            k for k in snap if "channel_frames" in k))
    # Ring chunks: n per allreduce move as >=1 segment each on the send
    # side; the 64KB-segment run must split chunks further.
    assert seg_counts["star"] == 0, seg_counts
    assert seg_counts["ring"] >= ITERS, seg_counts
    assert seg_counts["segring"] > seg_counts["ring"], seg_counts
    checks = {"rank": hvd.rank(), "bytes": got, "segments": seg_counts}
    hvd.shutdown()
    return checks


def worker_shm():
    """Shared-memory transport smoke, launched with NO HOROVOD_TRANSPORT
    set — the `auto` DEFAULT must engage shm between these co-located
    ranks by itself (the ROADMAP-flagged default-flip assertion): star
    over shm p2p, ring over the per-pair shm rings, and the intra-host
    arena — engine byte accounting stays EXACT on every path, and the
    per-transport counters let main() assert exact conservation: every
    shm byte one rank sent, the other received."""
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    n = hvd.size()
    expect_bytes = 0
    schedules = [
        ("star", {"HOROVOD_CPU_OPERATIONS": "star"}),
        # CPU_OPERATIONS=ring pins the per-pair shm RINGS (the arena
        # would otherwise win the op registry).
        ("shmring", {"HOROVOD_CPU_OPERATIONS": "ring",
                     "HOROVOD_RING_THRESHOLD": "0",
                     "HOROVOD_RING_SEGMENT_BYTES": "0"}),
        ("arena", {"HOROVOD_RING_THRESHOLD": "0"}),
    ]
    for name, env in schedules:
        os.environ.pop("HOROVOD_CPU_OPERATIONS", None)
        os.environ.update(env)
        for i in range(ITERS):
            x = np.full(COUNT, float(hvd.rank() + 1), np.float32)
            out = np.asarray(hvd.allreduce(
                x, name=f"ps.{name}.{i}", op=hvd.Sum))
            assert out.shape == (COUNT,), out.shape
            assert float(out[0]) == sum(range(1, n + 1)), (name, out[0])
            expect_bytes += x.nbytes
    hvd.barrier()
    snap = hvd.metrics()["metrics"]
    got = snap["horovod_allreduce_bytes_total"]
    assert got == expect_bytes, (
        f"allreduce_bytes_total drifted on shm: got {got}, "
        f"expected exactly {expect_bytes}")
    shm_sent = snap.get(
        'horovod_transport_bytes_total{direction="sent",transport="shm"}',
        0)
    shm_recv = snap.get(
        'horovod_transport_bytes_total{direction="recv",transport="shm"}',
        0)
    assert shm_sent > 0 and shm_recv > 0, (
        "data plane never rode shared memory", sorted(
            k for k in snap if "transport_bytes" in k))
    checks = {"rank": hvd.rank(), "bytes": got,
              "shm_sent": shm_sent, "shm_recv": shm_recv}
    hvd.shutdown()
    return checks


def worker_compression():
    """Wire-compression smoke over the pinned tcp plane: compressed
    RING and STAR legs with EXACT accounting of BOTH sides of the
    ledger — `horovod_allreduce_bytes_total` keeps counting negotiated
    INPUT bytes (codec-independent by design: the engine records what
    the user enqueued), while `horovod_wire_bytes_saved_total{codec=}`
    must equal the closed-form per-frame savings:

    * ring (np=n, COUNT fp32 elems, bf16): each rank sends one
      COUNT/n-elem chunk per reduce-scatter step and one per allgather
      step (n-1 each), saving 2 bytes/elem -> per rank per op
      2*(n-1)*(COUNT/n)*2 bytes;
    * star: a worker's gather frame saves COUNT*2; the root saves
      (n-1)*COUNT*2 on its result broadcast (its own gather
      contribution never touches a wire and must NOT count).

    Compression counters fold into the per-transport accounting as
    true wire bytes: the same schedule's tcp sent bytes must SHRINK
    vs an uncompressed control leg (asserted), because the transport
    counters see the encoded frames — nothing is estimated."""
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    n = hvd.size()
    os.environ.update({"HOROVOD_WIRE_COMPRESSION_MIN_BYTES": "0",
                       "HOROVOD_RING_THRESHOLD": "0",
                       "HOROVOD_RING_SEGMENT_BYTES": "0"})

    def tcp_sent(snap):
        return snap.get(
            'horovod_transport_bytes_total'
            '{direction="sent",transport="tcp"}', 0)

    expect_bytes = 0
    expect_saved = 0
    per_elem = 2  # fp32 -> bf16
    tcp_deltas = {}
    legs = [
        ("none_ring", "none", {}),
        ("ring", "bf16", {}),
        ("star", "bf16", {"HOROVOD_CPU_OPERATIONS": "star"}),
    ]
    for name, mode, env in legs:
        os.environ.pop("HOROVOD_CPU_OPERATIONS", None)
        os.environ.update(env)
        os.environ["HOROVOD_WIRE_COMPRESSION"] = mode
        before = tcp_sent(hvd.metrics()["metrics"])
        for i in range(ITERS):
            # rank+1 is exactly representable in bf16, so the reduced
            # values — and the zero error-feedback residuals — stay
            # exact and the correctness assert needs no tolerance.
            x = np.full(COUNT, float(hvd.rank() + 1), np.float32)
            out = np.asarray(hvd.allreduce(
                x, name=f"pcmp.{name}.{i}", op=hvd.Sum))
            assert out.shape == (COUNT,), out.shape
            assert float(out[0]) == sum(range(1, n + 1)), (name, out[0])
            expect_bytes += x.nbytes
            if mode == "bf16":
                if name == "ring":
                    expect_saved += 2 * (n - 1) * (COUNT // n) * per_elem
                else:  # star
                    expect_saved += (n - 1) * COUNT * per_elem \
                        if hvd.rank() == 0 else COUNT * per_elem
        hvd.barrier()
        tcp_deltas[name] = tcp_sent(hvd.metrics()["metrics"]) - before
    os.environ["HOROVOD_WIRE_COMPRESSION"] = "none"

    snap = hvd.metrics()["metrics"]
    got = snap["horovod_allreduce_bytes_total"]
    assert got == expect_bytes, (
        f"allreduce_bytes_total drifted under compression: got {got}, "
        f"expected exactly {expect_bytes}")
    saved = snap.get('horovod_wire_bytes_saved_total{codec="bf16"}', 0)
    assert saved == expect_saved, (
        f"wire_bytes_saved accounting drifted: got {saved}, expected "
        f"exactly {expect_saved}")
    # True-wire-bytes fold: same ring schedule, compressed frames ->
    # fewer tcp bytes on the wire than the uncompressed control.
    assert tcp_deltas["ring"] < tcp_deltas["none_ring"], tcp_deltas
    checks = {"rank": hvd.rank(), "bytes": got, "saved": saved,
              "tcp_ring": tcp_deltas["ring"],
              "tcp_none": tcp_deltas["none_ring"]}
    hvd.shutdown()
    return checks


def worker_traced():
    """Traced-collectives smoke (docs/running.md "Traced collectives"):
    with a REAL process-mode engine alive, a jitted shard_map gradient
    exchange over the worker's local 2-device mesh must dispatch to the
    XLA plane and leave the engine data plane UNTOUCHED — XLA owns the
    wire, so `horovod_allreduce_bytes_total` and the transport byte
    counters must not move while `horovod_traced_ops_total` does. An
    eager control op first proves the engine counters DO move when the
    engine is used (a zero-delta assert against dead counters would
    pass vacuously)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.parallel.mesh import create_mesh
    from horovod_tpu.utils.compat import shard_map

    hvd.init()
    n = hvd.size()

    def engine_bytes(snap):
        return snap.get("horovod_allreduce_bytes_total", 0)

    def data_frames(snap):
        # Frames on NUMERIC (data) channels only: ctrl/health frames
        # keep flowing regardless (heartbeats, telemetry piggyback) and
        # must not fail the zero-data-plane assert.
        total = 0
        for k, v in snap.items():
            if k.startswith("horovod_tcp_channel_frames_total"):
                label = k.split('channel="')[1].split('"')[0]
                if label.isdigit():
                    total += v
        return total

    def traced_ops(snap):
        return sum(v for k, v in snap.items()
                   if k.startswith("horovod_traced_ops_total"))

    # Control: the eager plane moves engine bytes.
    x = np.full(COUNT, float(hvd.rank() + 1), np.float32)
    out = np.asarray(hvd.allreduce(x, name="ptr.ctrl", op=hvd.Sum))
    assert float(out[0]) == sum(range(1, n + 1)), out[0]
    snap = hvd.metrics()["metrics"]
    assert engine_bytes(snap) == x.nbytes, snap.get(
        "horovod_allreduce_bytes_total")

    # Traced leg: local 2-device mesh, jitted psum exchange. The
    # barrier settles the control op's in-flight frames before the
    # before-snapshot.
    assert len(jax.devices()) >= 2, "worker needs 2 forced CPU devices"
    mesh = create_mesh({"hvd": 2}, devices=jax.devices()[:2])
    hvd.barrier()
    snap = hvd.metrics()["metrics"]
    before_engine = engine_bytes(snap)
    before_frames = data_frames(snap)
    before_traced = traced_ops(snap)

    step = jax.jit(shard_map(
        lambda v: hvd.allreduce(v, op=hvd.Sum),
        mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd")))
    g = jnp.arange(2 * COUNT, dtype=jnp.float32)
    for _ in range(ITERS):
        out_t = jax.block_until_ready(step(g))
    halves = np.asarray(g).reshape(2, -1)
    np.testing.assert_allclose(np.asarray(out_t),
                               np.tile(halves[0] + halves[1], 2))

    snap = hvd.metrics()["metrics"]
    traced_delta = traced_ops(snap) - before_traced
    engine_delta = engine_bytes(snap) - before_engine
    frames_delta = data_frames(snap) - before_frames
    assert traced_delta > 0, "traced dispatch never engaged"
    assert engine_delta == 0, (
        f"traced collectives leaked {engine_delta} bytes into the "
        "engine data plane — XLA owns the traced wire")
    assert frames_delta == 0, (
        f"traced collectives moved {frames_delta} frames on the "
        "engine's data channels")
    checks = {"rank": hvd.rank(), "bytes": int(x.nbytes),
              "traced_ops": int(traced_delta),
              "engine_delta": int(engine_delta),
              "data_frames_delta": int(frames_delta)}
    hvd.barrier()
    hvd.shutdown()
    return checks


def worker_hier():
    """Two-level hierarchical allreduce over a SIMULATED 2-host x
    2-slot topology (distinct HOROVOD_HOSTNAME per host): intra-host
    legs ride shm, inter-host legs ride tcp, across every cross
    schedule — slice-parallel, leader over per-pair rings, leader over
    the per-HOST arena, and compressed leader-arena. Each leg gets its
    own per-transport accounting contract:

    * slice / leader_rings: global shm conservation — every ring byte
      one rank wrote (headers included), its co-located peer consumed;
    * leader_arena (and its bf16 twin — arena legs ship full-width BY
      DESIGN, so the closed form is codec-independent): EXACT per-rank
      shm deltas per op — a member deposits its vector once (C bytes
      sent) and copies the bcast out (C recv); the leader reads every
      member's slot while reducing in place ((L-1)·C recv) and makes
      the bcast deposit (C sent). No shared-result hop, no leader
      deposit, no copy-out — the leg's whole point;
    * leader_arena_bf16: `wire_bytes_saved_total{codec="bf16"}` equals
      the closed-form INTER-HOST savings — the leaders' segmented
      cross ring sends 2(n_cross-1) chunks of COUNT/n_cross elems per
      op at 2 bytes saved per elem; members save nothing (their bytes
      never meet a wire).
    """
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    n = hvd.size()
    L = 2                      # launched as 2 hosts x 2 slots
    is_leader = hvd.rank() % L == 0
    expect_bytes = 0
    os.environ["HOROVOD_RING_THRESHOLD"] = "0"
    c_bytes = COUNT * 4

    def snap():
        return hvd.metrics()["metrics"]

    def shm(s, d):
        return s.get('horovod_transport_bytes_total'
                     f'{{direction="{d}",transport="shm"}}', 0)

    legs = [
        ("slice", {"HOROVOD_HIERARCHICAL_MODE": "slice",
                   "HOROVOD_HIER_ARENA": "off"}),
        ("leader_rings", {"HOROVOD_HIERARCHICAL_MODE": "leader",
                          "HOROVOD_HIER_ARENA": "off"}),
        ("leader_arena", {"HOROVOD_HIERARCHICAL_MODE": "leader",
                          "HOROVOD_HIER_ARENA": "auto"}),
        ("leader_arena_bf16", {"HOROVOD_HIERARCHICAL_MODE": "leader",
                               "HOROVOD_HIER_ARENA": "auto",
                               "HOROVOD_WIRE_COMPRESSION": "bf16",
                               "HOROVOD_WIRE_COMPRESSION_MIN_BYTES":
                                   "0"}),
    ]
    deltas = {}
    for name, env in legs:
        os.environ.update(env)
        hvd.barrier()
        before = snap()
        for i in range(ITERS):
            # rank+1 is exactly representable in bf16, so the
            # compressed leg's correctness assert needs no tolerance.
            x = np.full(COUNT, float(hvd.rank() + 1), np.float32)
            out = np.asarray(hvd.allreduce(
                x, name=f"ph.{name}.{i}", op=hvd.Sum))
            assert float(out[0]) == sum(range(1, n + 1)), (name, out[0])
            expect_bytes += x.nbytes
        hvd.barrier()
        after = snap()
        deltas[name] = {
            "sent": shm(after, "sent") - shm(before, "sent"),
            "recv": shm(after, "recv") - shm(before, "recv"),
            "saved": (after.get(
                'horovod_wire_bytes_saved_total{codec="bf16"}', 0)
                - before.get(
                    'horovod_wire_bytes_saved_total{codec="bf16"}', 0)),
            "arena_ops": (after.get("horovod_hier_arena_ops_total", 0)
                          - before.get("horovod_hier_arena_ops_total",
                                       0)),
        }
        os.environ["HOROVOD_WIRE_COMPRESSION"] = "none"

    # Per-pair-ring legs move nothing through the arena — but their
    # intra-host bytes MUST ride shm (a silent tcp fallback would make
    # the conservation assert below pass vacuously at 0 == 0).
    assert deltas["slice"]["arena_ops"] == 0, deltas["slice"]
    assert deltas["leader_rings"]["arena_ops"] == 0, deltas["leader_rings"]
    assert deltas["slice"]["sent"] > 0, deltas["slice"]
    assert deltas["leader_rings"]["sent"] > 0, deltas["leader_rings"]
    # Arena-legged leader: exact per-rank shm byte accounting (arena
    # counters carry no frame headers — deposits count as sent,
    # copy-outs as recv).
    for name in ("leader_arena", "leader_arena_bf16"):
        d = deltas[name]
        assert d["arena_ops"] == ITERS, (name, d)
        want_sent = ITERS * c_bytes
        want_recv = ITERS * ((L - 1) * c_bytes if is_leader else c_bytes)
        assert d["sent"] == want_sent, (name, d, want_sent)
        assert d["recv"] == want_recv, (name, d, want_recv)
    # Compressed leg: closed-form INTER-HOST savings only.
    n_cross = n // L
    want_saved = (ITERS * 2 * (n_cross - 1) * (COUNT // n_cross) * 2
                  if is_leader else 0)
    assert deltas["leader_arena_bf16"]["saved"] == want_saved, (
        deltas["leader_arena_bf16"], want_saved)
    assert deltas["leader_arena"]["saved"] == 0, deltas["leader_arena"]

    snap_end = snap()
    got = snap_end["horovod_allreduce_bytes_total"]
    assert got == expect_bytes, (
        f"allreduce_bytes_total drifted (hier): got {got}, "
        f"expected exactly {expect_bytes}")
    tcp_sent = snap_end.get(
        'horovod_transport_bytes_total{direction="sent",transport="tcp"}',
        0)
    assert tcp_sent > 0, "inter-host legs never rode tcp"
    checks = {"rank": hvd.rank(), "bytes": got,
              "ring_sent": deltas["slice"]["sent"]
              + deltas["leader_rings"]["sent"],
              "ring_recv": deltas["slice"]["recv"]
              + deltas["leader_rings"]["recv"],
              "arena_sent": deltas["leader_arena"]["sent"]
              + deltas["leader_arena_bf16"]["sent"],
              "saved": deltas["leader_arena_bf16"]["saved"]}
    hvd.shutdown()
    return checks


def worker_zero():
    """ZeRO-mode smoke (docs/running.md "ZeRO sharded optimizer
    state"): np=4 eager ``DistributedOptimizer(zero=1)`` steps with
    EXACT per-rank byte accounting on BOTH collective legs:

    * gradient leg: one grouped allreduce of the raw leaves per step,
      so `horovod_allreduce_bytes_total` grows by exactly
      ITERS x sum(leaf nbytes) per rank;
    * update leg: one allgather of this rank's updated segment plus
      the 1-element sentinel (empty shards must still gather), so
      `horovod_allgather_bytes_total` grows by exactly
      ITERS x (hi - lo + 1) x itemsize — (lo, hi) from the SAME
      element-block cut the optimizer uses (`_eager_cut`), so the
      assert pins the ownership math, not a re-derivation.

    Integer-valued gradients make the reduction exact, so the updates
    must be BITWISE equal to a local replicated adam control, and the
    `horovod_optimizer_state_bytes` gauges must show the measured
    sharded footprint at ~1/n of the replicated one."""
    import functools

    import numpy as np

    import jax
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.optim.zero import _eager_cut

    hvd.init()
    n = hvd.size()
    rank = hvd.rank()

    rng = np.random.RandomState(7)
    params = {
        "w": rng.randn(311, 17).astype(np.float32),
        "b": rng.randn(63).astype(np.float32),
        "emb": rng.randn(5000).astype(np.float32),
    }
    total = sum(v.size for v in params.values())
    lo, hi = _eager_cut(total, 4, n)[rank]

    inner = optax.adam(1e-3)
    tx = hvd.DistributedOptimizer(inner, zero=1)
    state = tx.init(params)
    ctl_state = inner.init(params)
    ctl_params = {k: v.copy() for k, v in params.items()}

    def snap():
        return hvd.metrics()["metrics"]

    before = snap()
    for i in range(ITERS):
        # rank-dependent INTEGER grads: the ring sum is exact in fp32
        # and /n is dyadic, so the zero path must match the local
        # replicated control bitwise — no tolerance.
        grads = {k: (np.int32(1) + np.arange(v.size, dtype=np.int32)
                     % 7 + rank + i).astype(np.float32).reshape(v.shape)
                 for k, v in params.items()}
        upd, state = tx.update(grads, state, params)
        mean = {k: functools.reduce(
            np.add, [(grads[k] - rank) + r for r in range(n)]) / n
            for k in grads}
        ctl_upd, ctl_state = inner.update(mean, ctl_state, ctl_params)
        for k in upd:
            assert np.array_equal(np.asarray(upd[k]),
                                  np.asarray(ctl_upd[k])), (
                f"zero update diverged from replicated control on {k!r}")
    hvd.barrier()
    after = snap()

    itemsize = 4  # fp32 accumulator — every param leaf is fp32
    want_ar = ITERS * total * itemsize
    got_ar = (after.get("horovod_allreduce_bytes_total", 0)
              - before.get("horovod_allreduce_bytes_total", 0))
    assert got_ar == want_ar, (
        f"zero gradient-leg accounting drifted: allreduce moved "
        f"{got_ar} bytes, closed form says exactly {want_ar}")
    want_ag = ITERS * (hi - lo + 1) * itemsize
    got_ag = (after.get("horovod_allgather_bytes_total", 0)
              - before.get("horovod_allgather_bytes_total", 0))
    assert got_ag == want_ag, (
        f"zero update-leg accounting drifted: allgather moved "
        f"{got_ag} bytes, closed form (segment {hi - lo} elems + "
        f"sentinel) says exactly {want_ag}")

    sharded = after.get(
        'horovod_optimizer_state_bytes{mode="sharded"}', 0)
    replicated = after.get(
        'horovod_optimizer_state_bytes{mode="replicated"}', 0)
    measured = sum(np.asarray(l).nbytes
                   for l in jax.tree.leaves(state.inner))
    assert sharded == measured, (sharded, measured)
    assert replicated > 0 and sharded < replicated / (n - 1), (
        f"sharded state {sharded} B is not ~1/{n} of the replicated "
        f"{replicated} B")
    checks = {"rank": rank, "allreduce_bytes": got_ar,
              "allgather_bytes": got_ag, "segment": [int(lo), int(hi)],
              "state_sharded": int(sharded),
              "state_replicated": int(replicated)}
    hvd.shutdown()
    return checks


def main_zero():
    """The ci.sh `perf_smoke zero` leg: np=4 eager ZeRO with exact
    two-leg byte accounting (its own leg so a zero-path regression
    names itself in CI output)."""
    import json

    from horovod_tpu.runner import run

    results = run(worker_zero, np=4, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_TCP_TIMEOUT_SECONDS": "120",
        "HOROVOD_TRANSPORT": "auto",
    })
    assert len(results) == 4, results
    # Every rank saw the same gradient-leg bytes; segments tile [0,
    # total) without overlap.
    assert all(r["allreduce_bytes"] == results[0]["allreduce_bytes"]
               for r in results), results
    segs = sorted(r["segment"] for r in results)
    assert segs[0][0] == 0, segs
    assert all(segs[i][1] == segs[i + 1][0]
               for i in range(len(segs) - 1)), segs
    total_state = sum(r["state_sharded"] for r in results)
    print("perf smoke OK (zero):", results)
    print(json.dumps({
        "metric": "perf_smoke_zero",
        "allreduce_bytes": results[0]["allreduce_bytes"],
        "allgather_bytes": [r["allgather_bytes"] for r in results],
        "state_sharded_total": total_state,
        "state_replicated": results[0]["state_replicated"],
    }))


def main():
    import json

    from horovod_tpu.runner import run

    results = run(worker, np=2, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_TCP_TIMEOUT_SECONDS": "60",
        # Explicit pin: the default transport is `auto` now, and this
        # stage's sendmsg/segment counters assert the raw socket plane.
        "HOROVOD_TRANSPORT": "tcp",
    })
    assert len(results) == 2, results
    assert all(r["bytes"] == results[0]["bytes"] for r in results), results
    print("perf smoke OK (tcp):", results)

    # Compression stage: tcp pinned (the per-transport shrink assert
    # compares raw socket bytes), codec engaged via env on every rank
    # (only rank 0's matters — the codec id rides the wire).
    cmp_results = run(worker_compression, np=2, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_TCP_TIMEOUT_SECONDS": "60",
        "HOROVOD_TRANSPORT": "tcp",
    })
    assert len(cmp_results) == 2, cmp_results
    assert all(r["bytes"] == cmp_results[0]["bytes"]
               for r in cmp_results), cmp_results
    print("perf smoke OK (compression):", cmp_results)

    # Traced stage: pinned tcp (the data-channel frame counters assert
    # the socket plane), 2 forced CPU devices per worker for the local
    # mesh. Proves the metrics.md claim: traced collectives do NOT ride
    # horovod_allreduce_bytes_total — XLA owns that wire.
    traced_results = run(worker_traced, np=2, extra_env={
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_TCP_TIMEOUT_SECONDS": "60",
        "HOROVOD_TRANSPORT": "tcp",
    })
    assert len(traced_results) == 2, traced_results
    assert all(r["engine_delta"] == 0 and r["traced_ops"] > 0
               for r in traced_results), traced_results
    print("perf smoke OK (traced):", traced_results)

    # Deliberately NO HOROVOD_TRANSPORT here: this stage doubles as the
    # default-route assertion — on a co-located mesh the `auto` default
    # must select shm on its own (worker_shm fails if no data byte ever
    # rode shared memory).
    shm_results = run(worker_shm, np=2, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_TCP_TIMEOUT_SECONDS": "60",
    })
    assert len(shm_results) == 2, shm_results
    assert all(r["bytes"] == shm_results[0]["bytes"]
               for r in shm_results), shm_results
    # Exact shm conservation: every byte (headers included) one rank
    # wrote into a ring or arena, its peer consumed.
    total_sent = sum(r["shm_sent"] for r in shm_results)
    total_recv = sum(r["shm_recv"] for r in shm_results)
    assert total_sent == total_recv, (
        f"shm byte conservation broken: sent {total_sent} != "
        f"recv {total_recv}")
    print("perf smoke OK (shm):", shm_results)

    # The simulated hosts are spawned locally: the LAUNCHER consults
    # HVDRUN_FORCE_LOCAL from its own env (extra_env only reaches the
    # workers).
    os.environ["HVDRUN_FORCE_LOCAL"] = "1"
    hier_results = run(worker_hier, np=4, hosts="hostA:2,hostB:2",
                       extra_env={
                           "JAX_PLATFORMS": "cpu",
                           "HOROVOD_CYCLE_TIME": "1",
                           "HOROVOD_TCP_TIMEOUT_SECONDS": "120",
                           "HOROVOD_TRANSPORT": "auto",
                           "HOROVOD_HIERARCHICAL_ALLREDUCE": "auto",
                           "HVDRUN_FORCE_LOCAL": "1",
                       })
    assert len(hier_results) == 4, hier_results
    assert all(r["bytes"] == hier_results[0]["bytes"]
               for r in hier_results), hier_results
    # Ring-legged legs conserve shm bytes globally; the arena legs'
    # exact (deliberately non-conserving) closed form was asserted
    # per rank inside the worker.
    assert (sum(r["ring_sent"] for r in hier_results)
            == sum(r["ring_recv"] for r in hier_results)), hier_results
    print("perf smoke OK (hier):", hier_results)
    print(json.dumps({
        "metric": "perf_smoke",
        "tcp_bytes": results[0]["bytes"],
        "shm_bytes": shm_results[0]["bytes"],
        "shm_conserved": total_sent,
        "hier_bytes": hier_results[0]["bytes"],
        "hier_wire_saved": sum(r["saved"] for r in hier_results),
        "traced_ops": sum(r["traced_ops"] for r in traced_results),
        "traced_engine_bytes_delta": sum(
            r["engine_delta"] for r in traced_results),
    }))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "zero":
        main_zero()
    else:
        main()
