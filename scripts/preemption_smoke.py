#!/usr/bin/env python
"""Preemption-drain smoke: an ANNOUNCED preemption must beat an
unannounced failure on every axis the drain plane promises
(docs/fault_tolerance.md "Announced preemption").

Phase 1 (graceful): four elastic workers train with a checkpoint
interval far larger than the run (so ONLY the drain's forced
checkpoint can produce a manifest); one worker receives the preemption
signal mid-run (``preempt:step=N`` chaos rule). Asserts:

  * the drained worker's final commit is durable — a complete manifest
    exists at step >= the preemption step (zero lost steps beyond the
    checkpoint interval, which never fired);
  * survivors finish at np=3 with the disruption attributed to the
    ``preemption`` badput bucket — the ``failure`` bucket stays 0;
  * the drained host collects no blacklist strike (the exit was the
    plan), and the driver exits 0.

Phase 2 (timeout comparison): the same scenario, but the worker
WEDGES (unannounced: process alive, heartbeats stop) so recovery must
wait out the liveness timeout. The run emits one JSON line comparing
the two goodput ratios; graceful must beat timeout.

    python scripts/preemption_smoke.py
    python scripts/preemption_smoke.py --preempt-host hostC --batches 12
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import pickle
import sys
import tempfile
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _incident_report():
    spec = importlib.util.spec_from_file_location(
        "incident_report",
        os.path.join(REPO, "scripts", "incident_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ordered(kinds, *want) -> bool:
    """True when `want` appears as an ordered subsequence of kinds."""
    i = 0
    for w in want:
        try:
            i = kinds.index(w, i) + 1
        except ValueError:
            return False
    return True

WORKER = textwrap.dedent("""
    import os, pickle, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.backend.elastic_env import spawn_identity
    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.common import fault_injection
    from horovod_tpu.elastic.state import ObjectState
    from horovod_tpu.utils import env as env_cfg

    TOTAL = int(os.environ["SMOKE_TOTAL_BATCHES"])
    hvd.init()
    state = ObjectState(batch=0, history=[])

    @hvd.elastic.run
    def train(state):
        while state.batch < TOTAL:
            hvd.allreduce(np.ones(2, np.float32), name="g")
            fault_injection.advance_step()  # doomed worker preempts/wedges
            state.history.append((hvd.rank(), hvd.size()))
            state.batch += 1
            state.commit()
            time.sleep(0.05)
        return list(state.history)

    hist = train(state)
    from horovod_tpu.common import goodput
    gp = goodput.active().view()
    rdv = RendezvousClient(env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR),
                           env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0))
    rdv.put("smoke_results", spawn_identity(),
            pickle.dumps({"hist": hist, "goodput": gp}))
    print(f"worker {spawn_identity()} done as rank {hvd.rank()} "
          f"size {hvd.size()}", flush=True)
""")

HOSTS = ["hostA", "hostB", "hostC", "hostD"]


def run_phase(args, fault_spec: str, ckpt_dir: str | None,
              events_dir: str | None = None):
    """One driver+4 workers run; returns (exit_code, results_by_host,
    driver) with the driver already stopped."""
    from horovod_tpu.common import events as events_mod
    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.launch import slot_env, spawn_worker
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    if events_dir is not None:
        # The driver journals lifecycle events as rank -1
        # (events_driver.jsonl); workers get the dir via env below.
        events_mod.set_current(events_mod.EventRecorder(
            rank=-1, spool_dir=events_dir, spool_seconds=0.1))
    server = RendezvousServer()
    port = server.start()
    driver = ElasticDriver(server, FixedHosts({h: 1 for h in HOSTS}),
                           min_np=2, max_np=4, poll_interval=0.25)

    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "worker.py")
        with open(script, "w") as f:
            f.write(WORKER)

        def create_worker(slot, extra_env):
            env = slot_env(slot, "127.0.0.1", port, elastic=True)
            env.update(extra_env)
            env["PYTHONPATH"] = REPO
            env["HVDRUN_FORCE_LOCAL"] = "1"
            env["HOROVOD_CYCLE_TIME"] = "1"
            env["HOROVOD_TCP_TIMEOUT_SECONDS"] = "0"  # unbounded: the point
            env["HOROVOD_HEARTBEAT_INTERVAL_SECONDS"] = str(args.hb_interval)
            env["HOROVOD_HEARTBEAT_MISS_LIMIT"] = str(args.hb_miss)
            env["SMOKE_TOTAL_BATCHES"] = str(args.batches)
            env.pop("HOROVOD_FAULT_INJECT", None)
            if ckpt_dir is not None:
                env["HOROVOD_CHECKPOINT_DIR"] = ckpt_dir
                # Interval >> batches: the only way a manifest appears
                # is the drain's forced save_now.
                env["HOROVOD_CHECKPOINT_INTERVAL_STEPS"] = "1000"
            if events_dir is not None:
                env["HOROVOD_EVENTS_DIR"] = events_dir
                env["HOROVOD_EVENTS_SPOOL_SECONDS"] = "0.1"
            if slot.hostname == args.preempt_host:
                env["HOROVOD_FAULT_INJECT"] = fault_spec
            handle = spawn_worker(slot, [sys.executable, script], env,
                                  prefix_output=False)
            return handle.proc

        try:
            driver.start(create_worker)
            code = driver.wait(timeout=args.deadline)
            results = {}
            for h in HOSTS:
                blob = server.handle_get(f"smoke_results/{h}:0")
                if blob is not None:
                    results[h] = pickle.loads(blob)
            return code, results, driver
        finally:
            driver.stop()
            server.stop()
            rec = events_mod.active()
            if events_dir is not None and rec is not None:
                rec.flush_spool()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--preempt-host", default="hostC")
    ap.add_argument("--preempt-step", type=int, default=3)
    ap.add_argument("--batches", type=int, default=12)
    ap.add_argument("--deadline", type=float, default=240.0,
                    help="wall-clock bound per phase")
    ap.add_argument("--hb-interval", type=float, default=0.5)
    ap.add_argument("--hb-miss", type=int, default=4)
    ap.add_argument("--ready-timeout", type=float, default=8.0)
    args = ap.parse_args()

    os.environ["HVDRUN_FORCE_LOCAL"] = "1"
    os.environ["HOROVOD_ELASTIC_READY_TIMEOUT"] = str(args.ready_timeout)
    os.environ["HOROVOD_DRAIN_GRACE_SECONDS"] = "15"

    from horovod_tpu.common.checkpoint import find_latest_manifest

    survivors = [h for h in HOSTS if h != args.preempt_host]
    ok = True

    # -- phase 1: announced preemption, graceful drain -----------------
    print("=== phase 1: graceful (announced preemption) ===", flush=True)
    with tempfile.TemporaryDirectory() as ckpt_dir, \
            tempfile.TemporaryDirectory() as events_dir:
        t0 = time.monotonic()
        code, results, driver = run_phase(
            args, f"preempt:step={args.preempt_step}", ckpt_dir,
            events_dir=events_dir)
        graceful_s = time.monotonic() - t0
        # The lifecycle chronicle (docs/events.md): merging every
        # journal must reconstruct the drill as one causal narrative.
        report = _incident_report().build_report([events_dir])
        kinds = [d["kind"] for d in report["events"]]
        print(f"chronicle: {len(kinds)} events from ranks "
              f"{report['summary']['ranks']}", flush=True)
        if not _ordered(kinds, "drain.notice", "drain.commit_barrier",
                        "drain.drained"):
            print("FAIL: chronicle lost the drain protocol order "
                  "(notice -> commit barrier -> drained): "
                  f"{kinds}", flush=True)
            ok = False
        # The manifest finalize (rank 0) races the drained rank's exit
        # — it only needs that rank's shard, not its liveness — so the
        # durability claim is barrier -> commit, not drained -> commit.
        if not _ordered(kinds, "drain.notice", "drain.commit_barrier",
                        "ckpt.commit"):
            print("FAIL: chronicle lost the durability order "
                  "(notice -> commit barrier -> ckpt.commit): "
                  f"{kinds}", flush=True)
            ok = False
        # Driver reaction: quarantine on the notice, then the shrunk
        # re-mesh. (No elastic.evict here: on a clean drain exit the
        # worker-exit activation re-meshes before the grace window
        # ends, and survivors restore/reset under the OLD epoch before
        # the new epoch's remesh — exactly what the sort shows.)
        if not _ordered(kinds, "drain.notice", "host.quarantine",
                        "elastic.remesh"):
            print("FAIL: chronicle lost the driver reaction order "
                  f"(notice -> quarantine -> remesh): {kinds}", flush=True)
            ok = False
        if not _ordered(kinds, "elastic.restore", "elastic.reset",
                        "elastic.remesh"):
            print("FAIL: chronicle lost the recovery order "
                  f"(restore -> reset -> remesh): {kinds}", flush=True)
            ok = False
        restores = [d for d in report["events"]
                    if d["kind"] == "elastic.restore"]
        if not any((d.get("attrs") or {}).get("peer_drained")
                   for d in restores):
            print("FAIL: no survivor's elastic.restore was attributed "
                  f"to a draining peer: {restores}", flush=True)
            ok = False
        if "drain.peer" not in kinds:
            print("FAIL: no survivor journaled drain.peer", flush=True)
            ok = False
        if code != 0:
            print(f"FAIL: graceful phase driver exit {code}", flush=True)
            ok = False
        found = find_latest_manifest(ckpt_dir)
        if found is None:
            print("FAIL: no manifest — the drain's forced checkpoint "
                  "never committed", flush=True)
            ok = False
            manifest_step = None
        else:
            manifest_step, manifest, _ = found
            print(f"drain checkpoint: manifest at step {manifest_step} "
                  f"({len(manifest['shards'])} shards)", flush=True)
            if manifest_step < args.preempt_step:
                print(f"FAIL: manifest step {manifest_step} < preemption "
                      f"step {args.preempt_step}: steps were lost",
                      flush=True)
                ok = False
            if len(manifest["shards"]) != len(HOSTS):
                print(f"FAIL: drain manifest has "
                      f"{len(manifest['shards'])} shards, expected "
                      f"{len(HOSTS)} — the doomed rank's shard is not the "
                      "one that committed", flush=True)
                ok = False
        graceful_ratio = None
        for h in survivors:
            doc = results.get(h)
            if doc is None:
                print(f"FAIL: survivor {h} reported no result", flush=True)
                ok = False
                continue
            hist, gp = doc["hist"], doc["goodput"]
            preempt_bad = gp["badput"]["preemption_seconds"]
            failure_bad = gp["badput"]["restart_downtime_seconds"]
            ratio = gp["goodput"]["ratio"]
            print(f"{h}: np={hist[-1][1]} preemption badput "
                  f"{preempt_bad:.2f}s failure badput {failure_bad:.2f}s",
                  flush=True)
            if hist[-1][1] != 3:
                print(f"FAIL: survivor {h} finished at np={hist[-1][1]}, "
                      "not 3", flush=True)
                ok = False
            if preempt_bad <= 0:
                print(f"FAIL: survivor {h} recorded no preemption badput",
                      flush=True)
                ok = False
            if failure_bad > 0:
                print(f"FAIL: survivor {h} attributed the announced drain "
                      f"to the failure bucket ({failure_bad:.2f}s)",
                      flush=True)
                ok = False
            if ratio is not None and (graceful_ratio is None
                                      or ratio < graceful_ratio):
                graceful_ratio = ratio  # worst survivor = honest bound
        if driver.host_manager.blacklist_strikes(args.preempt_host):
            print(f"FAIL: drained host {args.preempt_host} collected a "
                  "blacklist strike", flush=True)
            ok = False

    # -- phase 2: unannounced wedge, liveness-timeout recovery ---------
    print("=== phase 2: timeout (unannounced wedge) ===", flush=True)
    t0 = time.monotonic()
    code, results, _ = run_phase(
        args, f"wedge:step={args.preempt_step}", None)
    timeout_s = time.monotonic() - t0
    if code != 0:
        print(f"FAIL: timeout phase driver exit {code}", flush=True)
        ok = False
    timeout_ratio = None
    for h in survivors:
        doc = results.get(h)
        if doc is None:
            print(f"FAIL: survivor {h} reported no result (timeout phase)",
                  flush=True)
            ok = False
            continue
        ratio = doc["goodput"]["goodput"]["ratio"]
        if ratio is not None and (timeout_ratio is None
                                  or ratio < timeout_ratio):
            timeout_ratio = ratio

    # -- the comparison line -------------------------------------------
    line = {
        "graceful_goodput_ratio": graceful_ratio,
        "timeout_goodput_ratio": timeout_ratio,
        "graceful_wall_seconds": round(graceful_s, 1),
        "timeout_wall_seconds": round(timeout_s, 1),
        "manifest_step": manifest_step,
        "preempt_step": args.preempt_step,
    }
    print("PREEMPTION_SMOKE " + json.dumps(line), flush=True)
    if graceful_ratio is None or timeout_ratio is None:
        print("FAIL: missing a goodput ratio for the comparison",
              flush=True)
        ok = False
    elif graceful_ratio <= timeout_ratio:
        print(f"FAIL: graceful goodput ratio {graceful_ratio:.3f} did not "
              f"beat the timeout path {timeout_ratio:.3f}", flush=True)
        ok = False
    print("PASS" if ok else "FAIL", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
