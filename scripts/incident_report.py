#!/usr/bin/env python
"""Incident reports that reconstruct themselves (docs/events.md).

Point this at the directory an incident left behind — per-rank JSONL
event journals (``HOROVOD_EVENTS_DIR``), flight-recorder dumps and the
stitched ``postmortem.json`` (``HOROVOD_TRACE_DIR``); one directory or
two — and it merges every source into a single causally-ordered
chronicle:

* events are deduped by ``(rank, seq)`` across sources (the same event
  can appear in a journal AND in a flight dump's lifecycle tail);
* per-rank wall-clock skew comes from ``postmortem.json``'s
  ``per_rank.skew_ns`` (the health plane's RTT-estimated offsets,
  already applied to the stitched trace lanes) when present;
* ordering is ``(epoch, step, skew-adjusted wall, rank, seq)`` — epoch
  and step cursor are collectively agreed, so a PR 16 preemption drill
  reads as one narrative regardless of whose clock was fast:
  notice -> commit barrier -> drained -> quarantine -> re-mesh ->
  restore -> replay.

Usage:

    python scripts/incident_report.py /path/to/dir [more dirs...]
    python scripts/incident_report.py DIR --json        # machine form
    python scripts/incident_report.py DIR --limit 200

Text output is the chronicle plus a header summarizing the verdict,
sources and per-rank journal health (events, drops, skew). ``--json``
emits ``{"summary": ..., "events": [...]}`` for tooling.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from horovod_tpu.common import events as events_mod  # noqa: E402

POSTMORTEM = "postmortem.json"
FLIGHT_GLOB = "flight_rank*.json"


# -- sources ------------------------------------------------------------
def load_journals(directory: str) -> Dict[int, List[dict]]:
    """Every ``events_rank*.jsonl`` / ``events_driver.jsonl`` journal
    under `directory`, keyed by the rank recorded IN each event (a
    journal written before an elastic renumber can carry several)."""
    by_rank: Dict[int, List[dict]] = {}
    pattern = os.path.join(directory,
                           events_mod.JOURNAL_PREFIX + "*.jsonl")
    paths = sorted(glob.glob(pattern))
    driver = os.path.join(directory, events_mod.DRIVER_JOURNAL)
    if os.path.exists(driver):
        paths.append(driver)
    for path in paths:
        for d in events_mod.read_journal(path):
            by_rank.setdefault(int(d.get("rank", -1)), []).append(d)
    return by_rank


def load_flight_lifecycles(directory: str) -> Dict[int, List[dict]]:
    """The ``lifecycle`` tail each flight dump carries — the only event
    source when no spool dir was configured."""
    by_rank: Dict[int, List[dict]] = {}
    for path in sorted(glob.glob(os.path.join(directory, FLIGHT_GLOB))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        r = int(doc.get("rank", -1))
        for d in doc.get("lifecycle") or []:
            if isinstance(d, dict) and "kind" in d:
                by_rank.setdefault(int(d.get("rank", r)), []).append(d)
    return by_rank


def load_postmortem(directory: str) -> Optional[dict]:
    path = os.path.join(directory, POSTMORTEM)
    try:
        with open(path) as f:
            meta = json.load(f).get("horovod_postmortem")
            return meta if isinstance(meta, dict) else None
    except (OSError, ValueError):
        return None


def skews_from_postmortem(pm: Optional[dict]) -> Dict[int, int]:
    """rank -> wall-skew ns, as the stitcher computed it (RTT-estimated
    where the health plane had a sample; 0 = trust the wall clock)."""
    out: Dict[int, int] = {}
    for r, d in ((pm or {}).get("per_rank") or {}).items():
        try:
            out[int(r)] = int(d.get("skew_ns", 0))
        except (TypeError, ValueError):
            continue
    return out


# -- the merge ----------------------------------------------------------
def merge_chronicle(sources: List[Dict[int, List[dict]]],
                    skews: Optional[Dict[int, int]] = None
                    ) -> List[dict]:
    """Merge event dicts from several sources into one causally-ordered
    chronicle. Dedup by (rank, seq) — first source wins (pass journals
    before flight tails: journals carry the complete history). The sort
    is FleetEvents.merged's (events.causal_order): collectively-agreed
    epoch and step cursor first, skew-adjusted wall only breaks ties
    inside a cell, with step-less control-plane events interleaved at
    their wall position."""
    skews = skews or {}
    seen: set = set()
    out: List[dict] = []
    for src in sources:
        for r, evs in src.items():
            for d in evs:
                key: Tuple[int, int] = (r, int(d.get("seq", -1)))
                if key in seen:
                    continue
                seen.add(key)
                d = dict(d)
                d["rank"] = r
                d["adj_wall_ns"] = (int(d.get("wall_ns", 0))
                                    - skews.get(r, 0))
                out.append(d)
    return events_mod.causal_order(out)


def build_report(directories: List[str]) -> dict:
    """Everything the renderers need, from one or more incident dirs."""
    journals: Dict[int, List[dict]] = {}
    flights: Dict[int, List[dict]] = {}
    pm = None
    for directory in directories:
        for r, evs in load_journals(directory).items():
            journals.setdefault(r, []).extend(evs)
        for r, evs in load_flight_lifecycles(directory).items():
            flights.setdefault(r, []).extend(evs)
        if pm is None:
            pm = load_postmortem(directory)
    skews = skews_from_postmortem(pm)
    chron = merge_chronicle([journals, flights], skews)
    summary = {
        "directories": list(directories),
        "events": len(chron),
        "ranks": sorted({d["rank"] for d in chron}),
        "journal_ranks": sorted(journals),
        "flight_ranks": sorted(flights),
        "skew_ns": {str(r): s for r, s in sorted(skews.items())},
        "verdict": (pm or {}).get("verdict", ""),
    }
    return {"summary": summary, "events": chron}


# -- rendering ----------------------------------------------------------
def render_text(report: dict, limit: Optional[int] = None) -> str:
    s = report["summary"]
    chron = report["events"]
    if limit is not None:
        chron = chron[-limit:]
    lines = ["incident report — " + ", ".join(s["directories"]),
             f"events: {s['events']}  ranks: {s['ranks']}  "
             f"(journals: {s['journal_ranks']}, "
             f"flight dumps: {s['flight_ranks']})"]
    if s["verdict"]:
        lines.append(f"verdict: {s['verdict']}")
    if any(s["skew_ns"].values()):
        lines.append("clock skew applied (ns): " + ", ".join(
            f"r{r}={v}" for r, v in s["skew_ns"].items() if v))
    lines.append("=" * 72)
    t0 = chron[0]["adj_wall_ns"] if chron else 0
    for d in chron:
        attrs = d.get("attrs") or {}
        extras = " ".join(f"{k}={attrs[k]}" for k in sorted(attrs))
        lines.append(
            "+{t:9.3f}s  e{epoch:<3} step {step:<6} r{rank:<3} "
            "{sev:<5} {kind:<22} {extras}".format(
                t=(d["adj_wall_ns"] - t0) / 1e9,
                epoch=d.get("epoch", -1), step=d.get("step", 0),
                rank=d["rank"], sev=d.get("sev", ""),
                kind=d.get("kind", "?"), extras=extras).rstrip())
    if not chron:
        lines.append("(no lifecycle events found — was "
                     "HOROVOD_EVENTS_DIR or HOROVOD_TRACE_DIR set?)")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("directories", nargs="+",
                   help="incident dirs: HOROVOD_EVENTS_DIR and/or "
                        "HOROVOD_TRACE_DIR (may be the same)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the merged chronicle as JSON")
    p.add_argument("--limit", type=int, default=None,
                   help="show only the newest N events (text mode)")
    args = p.parse_args(argv)
    report = build_report(args.directories)
    if args.as_json:
        json.dump(report, sys.stdout, indent=1)
        print()
    else:
        print(render_text(report, limit=args.limit))
    return 0 if report["events"] else 1


if __name__ == "__main__":
    sys.exit(main())
