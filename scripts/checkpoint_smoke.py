#!/usr/bin/env python
"""Durability smoke: kill ALL ranks mid-training, restart the job, and
assert it resumes at the last committed checkpoint with bitwise state
parity and no partial-checkpoint debris (docs/checkpoint.md).

The kill-all-job scenario the elastic plane alone cannot survive:

1. **Phase 1** — N workers train a deterministic update rule under
   ``@hvd.elastic.run`` with ``HOROVOD_CHECKPOINT_DIR`` set; every rank
   carries a ``kill:step=K`` fault rule, so the WHOLE JOB dies at step
   K (rendezvous server included — its KV does not survive either).
2. The harness checks a complete manifest was committed at some step
   S <= K and that the checkpoint's arrays match the committed partial
   sum the update rule implies.
3. **Phase 2** — a fresh rendezvous server + fresh workers, same
   checkpoint dir, no fault rules. Every rank must restore at exactly
   S (reported params compared BITWISE against the manifest's shards),
   train to completion, and agree on the final weights — which must
   equal an uninterrupted run's, bit for bit.
4. The checkpoint dir must hold no ``*.tmp.*`` debris and no orphan
   shard dirs (the kill mid-write left some; commit-time GC cleans).

``--overhead`` instead measures commit-path overhead in-process: a
commit loop over an ``--mb``-sized pytree with checkpointing off vs
on (background writes overlapped), as order-alternated paired rounds
whose median is the verdict. The acceptance bar is <5%.

    python scripts/checkpoint_smoke.py
    python scripts/checkpoint_smoke.py --np 2 --kill-step 5 --interval 2
    python scripts/checkpoint_smoke.py --overhead --mb 8
    python scripts/checkpoint_smoke.py --overhead --step-mode blas
"""
from __future__ import annotations

import argparse
import json
import os
import pickle
import subprocess
import sys
import tempfile
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORKER = textwrap.dedent("""
    import os, pickle, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.common import fault_injection
    from horovod_tpu.elastic.state import JaxState
    from horovod_tpu.utils import env as env_cfg

    TOTAL = int(os.environ["SMOKE_TOTAL_STEPS"])
    hvd.init()
    rdv = RendezvousClient(env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR),
                           env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0))
    state = JaxState(params={"w": np.zeros((4, 8), np.float32)}, batch=0)
    reported = {"resume": False}

    @hvd.elastic.run
    def train(state):
        if not reported["resume"]:
            reported["resume"] = True
            # Where did this incarnation start, and with which bits?
            rdv.put("smoke_restored", str(hvd.rank()), pickle.dumps(
                (state.batch, state.params["w"].tobytes())))
        while state.batch < TOTAL:
            # Deterministic update: w += (batch+1); the allreduce keeps
            # the data plane (and its failure modes) in the loop.
            g = hvd.allreduce(
                np.full((4, 8), float(state.batch + 1), np.float32),
                name="g")
            state.params = {"w": state.params["w"] + np.asarray(g)}
            state.batch += 1
            state.commit()
            fault_injection.advance_step()  # kill-all fires here
        return state.params["w"]

    w = train(state)
    rdv.put("smoke_final", str(hvd.rank()),
            pickle.dumps((state.batch, np.asarray(w).tobytes())))
    # Goodput plane (docs/goodput.md): rank 0's ledger is the one that
    # loads the durable stamp, so a phase-2 (restarted) job reports the
    # kill-all's downtime and the replayed steps after restore here.
    if hvd.rank() == 0:
        from horovod_tpu.common import goodput
        rdv.put("smoke_goodput", "0", pickle.dumps(goodput.active().view()))
    print(f"rank {hvd.rank()}: finished at batch {state.batch}", flush=True)
""")


def _spawn_world(np_, port, ckpt_dir, total, interval, kill_step=None):
    from horovod_tpu.runner.hosts import get_host_assignments, parse_hosts
    from horovod_tpu.runner.launch import slot_env

    with open(os.path.join(ckpt_dir, "..", "worker.py"), "w") as f:
        f.write(WORKER)
    script = os.path.join(ckpt_dir, "..", "worker.py")
    slots = get_host_assignments(parse_hosts(f"localhost:{np_}"), np_)
    procs = {}
    for slot in slots:
        env = dict(os.environ)
        env.update(slot_env(slot, "127.0.0.1", port))
        env["PYTHONPATH"] = REPO
        env["HVDRUN_FORCE_LOCAL"] = "1"
        env["HOROVOD_CYCLE_TIME"] = "1"
        env["HOROVOD_TCP_TIMEOUT_SECONDS"] = "10"
        env["HOROVOD_CHECKPOINT_DIR"] = ckpt_dir
        env["HOROVOD_CHECKPOINT_INTERVAL_STEPS"] = str(interval)
        env["HOROVOD_CHECKPOINT_FSYNC"] = "0"  # CI disks; protocol unchanged
        env["SMOKE_TOTAL_STEPS"] = str(total)
        env.pop("HOROVOD_FAULT_INJECT", None)
        if kill_step is not None:
            env["HOROVOD_FAULT_INJECT"] = f"kill:step={kill_step}"
        procs[slot.rank] = subprocess.Popen([sys.executable, script],
                                            env=env)
    return procs


def _expected_w(upto):
    import numpy as np

    w = np.zeros((4, 8), np.float32)
    for b in range(upto):
        w = w + np.full((4, 8), float(b + 1), np.float32)
    return w


def run_killall(args) -> int:
    import numpy as np

    from horovod_tpu.common import checkpoint as ck
    from horovod_tpu.runner.rendezvous_server import RendezvousServer
    from horovod_tpu.utils import atomic_file

    td = tempfile.mkdtemp(prefix="hvd_ckpt_smoke_")
    ckpt_dir = os.path.join(td, "ckpt")
    os.makedirs(ckpt_dir)

    # ---- phase 1: the whole job dies at kill_step -------------------
    server = RendezvousServer()
    port = server.start()
    procs = _spawn_world(args.np_, port, ckpt_dir, args.steps,
                         args.interval, kill_step=args.kill_step)
    print(f"phase 1: {args.np_} workers; ALL ranks die at step "
          f"{args.kill_step}", flush=True)
    deadline = time.monotonic() + 300
    for rank, p in procs.items():
        p.wait(timeout=max(deadline - time.monotonic(), 1.0))
    codes = {r: p.returncode for r, p in sorted(procs.items())}
    print(f"phase 1 exits: {codes}", flush=True)
    server.stop()  # the KV dies with the job: true whole-job loss
    if any(c == 0 for c in codes.values()):
        print("FAIL: a worker finished before the kill-all", flush=True)
        return 1

    found = ck.find_latest_manifest(ckpt_dir)
    if found is None:
        print("FAIL: no complete checkpoint was committed before the "
              "kill", flush=True)
        return 1
    step0, manifest, _ = found
    print(f"last committed checkpoint: step {step0} "
          f"({len(manifest['shards'])} shards)", flush=True)
    if not (0 < step0 <= args.kill_step):
        print(f"FAIL: committed step {step0} outside (0, "
              f"{args.kill_step}]", flush=True)
        return 1
    objects, trees = ck.load_checkpoint_arrays(ckpt_dir, manifest)
    w_ckpt = trees["params"][0]
    if w_ckpt.tobytes() != _expected_w(step0).tobytes():
        print("FAIL: checkpoint arrays != the committed partial sum",
              flush=True)
        return 1

    # ---- phase 2: restart from nothing but the files ----------------
    server = RendezvousServer()
    port = server.start()
    procs = _spawn_world(args.np_, port, ckpt_dir, args.steps,
                         args.interval)
    print(f"phase 2: fresh job over the same checkpoint dir", flush=True)
    ok = True
    deadline = time.monotonic() + 300
    for rank, p in sorted(procs.items()):
        try:
            p.wait(timeout=max(deadline - time.monotonic(), 1.0))
        except subprocess.TimeoutExpired:
            print(f"FAIL: rank {rank} hung on restart", flush=True)
            p.kill()
            ok = False
    for rank in sorted(procs):
        blob = server.handle_get(f"smoke_restored/{rank}")
        if blob is None:
            print(f"FAIL: rank {rank} never reported its resume point",
                  flush=True)
            ok = False
            continue
        rstep, rbytes = pickle.loads(blob)
        bitwise = rbytes == w_ckpt.tobytes()
        print(f"rank {rank}: resumed at step {rstep} "
              f"(bitwise parity with manifest: {bitwise})", flush=True)
        ok = ok and rstep == step0 and bitwise
    expect_final = _expected_w(args.steps).tobytes()
    for rank in sorted(procs):
        blob = server.handle_get(f"smoke_final/{rank}")
        if blob is None:
            print(f"FAIL: rank {rank} reported no final state", flush=True)
            ok = False
            continue
        fstep, fbytes = pickle.loads(blob)
        match = fbytes == expect_final
        print(f"rank {rank}: finished at step {fstep} "
              f"(final weights == uninterrupted run: {match})", flush=True)
        ok = ok and fstep == args.steps and match

    # ---- goodput ledger audit (docs/goodput.md) ---------------------
    # The restarted job's rank-0 ledger resumed from the durable stamp
    # phase 1 wrote next to the checkpoints: the kill-all's downtime
    # and the steps replayed between the restored manifest and the
    # pre-crash step cursor must be attributed, and the goodput ratio
    # must be < 1 and consistent with wall-clock (buckets + goodput
    # sum to the job's wall within clamping tolerance).
    blob = server.handle_get("smoke_goodput/0")
    if blob is None:
        print("FAIL: rank 0 reported no goodput ledger", flush=True)
        ok = False
    else:
        gp = pickle.loads(blob)
        bad = gp["badput"]
        downtime = bad["restart_downtime_seconds"]
        replayed = bad["replayed_steps"]
        expect_replay = args.kill_step - step0
        ratio = gp["goodput"]["ratio"]
        wall = gp["wall_seconds"]
        # In-step exposed/stall only: out-of-step waits already live
        # inside other_seconds' wall time (the partition the ledger
        # defines).
        acct = (gp["goodput"]["seconds"]
                + bad["exposed_comm_in_step_seconds"]
                + bad["ckpt_stall_in_step_seconds"]
                + bad["replay_seconds"]
                + bad["restart_downtime_seconds"] + bad["other_seconds"])
        print(f"goodput ledger: generation {gp['generation']}, "
              f"wall {wall:.1f}s, ratio {ratio}, "
              f"downtime {downtime:.2f}s, replayed {replayed} steps "
              f"(expected {expect_replay}), accounted {acct:.1f}s",
              flush=True)
        if gp["generation"] < 2:
            print("FAIL: ledger did not survive the restart", flush=True)
            ok = False
        if downtime <= 0:
            print("FAIL: kill-all downtime not attributed", flush=True)
            ok = False
        if replayed != expect_replay:
            print(f"FAIL: replayed steps {replayed} != {expect_replay}",
                  flush=True)
            ok = False
        if not (ratio is not None and 0 <= ratio < 1):
            print("FAIL: goodput ratio not in [0, 1)", flush=True)
            ok = False
        # The ledger's buckets partition wall-clock (up to the >=0
        # clamps): accounted time within 10% of wall.
        if not (0.9 * wall <= acct <= 1.1 * wall + 0.5):
            print(f"FAIL: buckets sum to {acct:.1f}s but wall is "
                  f"{wall:.1f}s", flush=True)
            ok = False
    server.stop()

    # ---- debris audit ------------------------------------------------
    manifests = {s for s, _ in ck.list_manifests(ckpt_dir)}
    for root, dirs, files in os.walk(ckpt_dir):
        for f in files:
            if atomic_file.is_tmp_debris(f):
                print(f"FAIL: tmp debris {os.path.join(root, f)}",
                      flush=True)
                ok = False
    for name in os.listdir(ckpt_dir):
        if name.startswith(ck.STEP_DIR_PREFIX):
            s = int(name[len(ck.STEP_DIR_PREFIX):])
            if s not in manifests:
                print(f"FAIL: orphan shard dir {name} (no manifest)",
                      flush=True)
                ok = False
    print("PASS" if ok else "FAIL", flush=True)
    return 0 if ok else 1


def run_overhead(args) -> int:
    """Per-step overhead of the durability plane, checkpointing off vs
    on. Each "step" is a fixed amount of real compute (matmul reps
    calibrated to ``--step-ms``, the scale of a bench.py model step) +
    ``state.commit()``'s host-copy save; the checkpointed run adds the
    snapshot/enqueue on the training thread and the pickle+write on the
    background writer, whose cost must overlap the compute — the <5%
    acceptance bar (ROADMAP item 5)."""
    import numpy as np

    from horovod_tpu.common import checkpoint as ck
    from horovod_tpu.elastic.state import JaxState

    n = max(int(args.mb * (1 << 20) / 4 / 4), 1)
    params = {f"w{i}": np.random.default_rng(i).standard_normal(
        n, dtype=np.float32) for i in range(4)}
    steps = args.overhead_steps
    interval = args.overhead_interval

    # Fixed work per step at ~step_ms, the scale of a bench.py model
    # step. Default `sleep` models the acceptance context — a
    # device-bound step: the training thread blocks on the accelerator
    # and the host CPU is free, which is exactly what the background
    # writer overlaps with (measured overhead = training-thread
    # snapshot cost + GIL slices the pickler steals). `blas` instead
    # burns host CPU (a CPU-bound trainer): the informational
    # worst case — on a 1-core CI box writer CPU cannot overlap
    # anything and box-load noise dominates.
    if args.step_mode == "sleep":
        def work():
            time.sleep(args.step_ms / 1000.0)
    else:
        k = 700
        rng = np.random.default_rng(0)
        ma = rng.standard_normal((k, k)).astype(np.float32)
        mb_ = rng.standard_normal((k, k)).astype(np.float32)
        ma @ mb_  # BLAS warm-up (pool spin-up skews the calibration)
        t0 = time.perf_counter()
        for _ in range(3):
            ma @ mb_
        per = (time.perf_counter() - t0) / 3
        reps = max(round(args.step_ms / 1000.0 / per), 1)

        def work():
            for _ in range(reps):
                ma @ mb_

    def loop(mgr):
        st = JaxState(params=params, batch=0)
        t0 = time.perf_counter()
        for i in range(steps):
            work()  # stand-in model step
            st.batch = i
            st.save()
            if mgr is not None:
                mgr.maybe_save(st)
        if mgr is not None:
            mgr.flush(timeout=120)
        return time.perf_counter() - t0

    # Order-alternated paired rounds, median overhead (the repo's
    # measurement idiom — see benchmarks.md): a sequential base-then-
    # checkpointed pair measures box-load drift as much as checkpoint
    # cost on a shared CI box; alternation cancels the drift and the
    # median rejects the outlier rounds.
    td = tempfile.mkdtemp(prefix="hvd_ckpt_overhead_")
    rounds = []
    checkpoints = 0
    for i in range(args.overhead_rounds):
        mgr = ck.CheckpointManager(os.path.join(td, f"ckpt{i}"), rank=0,
                                   size=1, interval_steps=interval,
                                   commit_timeout=60, fsync=False)
        # Delta, not value: the telemetry registry dedupes counters by
        # name, so every round's manager shares one counter.
        w0 = int(mgr._m_writes.value)
        try:
            if i % 2 == 0:
                base = loop(None)
                with_ckpt = loop(mgr)
            else:
                with_ckpt = loop(mgr)
                base = loop(None)
            checkpoints += int(mgr._m_writes.value) - w0
        finally:
            mgr.stop()
        rounds.append({
            "baseline_s": round(base, 4),
            "checkpointed_s": round(with_ckpt, 4),
            "overhead_pct": round((with_ckpt - base) / base * 100.0, 2),
        })
    pcts = sorted(r["overhead_pct"] for r in rounds)
    overhead = pcts[len(pcts) // 2]
    print(json.dumps({
        "pytree_mb": args.mb, "steps_per_loop": steps,
        "step_ms_target": args.step_ms,
        "interval_steps": interval,
        "checkpoints_written": checkpoints,
        "rounds": rounds,
        "median_overhead_pct": overhead,
    }, indent=1), flush=True)
    ok = overhead < 5.0
    print("PASS" if ok else "FAIL (median overhead >= 5%)", flush=True)
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--np", dest="np_", type=int, default=2,
                    help="world size (default 2)")
    ap.add_argument("--steps", type=int, default=14,
                    help="total training steps")
    ap.add_argument("--kill-step", type=int, default=7,
                    help="step at which EVERY rank dies")
    ap.add_argument("--interval", type=int, default=2,
                    help="HOROVOD_CHECKPOINT_INTERVAL_STEPS")
    ap.add_argument("--overhead", action="store_true",
                    help="measure commit-path overhead instead")
    ap.add_argument("--mb", type=float, default=8.0,
                    help="pytree size for --overhead (MB)")
    ap.add_argument("--overhead-steps", type=int, default=60)
    ap.add_argument("--overhead-rounds", type=int, default=5,
                    help="order-alternated paired rounds; the median "
                         "overhead is the verdict")
    ap.add_argument("--step-mode", choices=("sleep", "blas"),
                    default="sleep",
                    help="stand-in step: 'sleep' = device-bound (the "
                         "TPU acceptance context; host CPU free for "
                         "the writer), 'blas' = CPU-bound worst case")
    ap.add_argument("--step-ms", type=float, default=50.0,
                    help="simulated compute per step for --overhead")
    ap.add_argument("--overhead-interval", type=int, default=20,
                    help="checkpoint interval for --overhead. The "
                         "default (an 8MB checkpoint per second of "
                         "50ms steps) is already far more aggressive "
                         "than any production cadence; the ~15-20ms "
                         "of wall each checkpoint steals from the "
                         "training thread amortizes over it")
    args = ap.parse_args()
    if args.overhead:
        return run_overhead(args)
    return run_killall(args)


if __name__ == "__main__":
    sys.exit(main())
