"""Per-component ms breakdown of the flagship GPT-2 seq-2048 flash
train step (the r5 analogue of the r4 ResNet ms-by-ms closure,
docs/benchmarks.md:53-94; ref methodology: docs/benchmarks.rst:16-43).

Times ablation variants of the SAME step on the real chip so each
subtraction isolates one component:

  full            flash step, lm_loss (the bench headline step)
  loss_mean       xent replaced by mean(logits): full - this = softmax
                  cross-entropy cost (fwd softmax + bwd dlogits forming)
  tiny_vocab      vocab 512: full - this ~= the whole lm-head region
                  (logits matmul fwd + 2 bwd matmuls + loss at V=50257)

The AdamW share has no ablation (removing the update changes the
program globally); it is bounded analytically in docs/benchmarks.md.
For bucket-level attribution use jax.profiler.trace around one scan
chunk and aggregate the device lane — the r5 profile tables in
docs/benchmarks.md were produced that way.

Usage: python scripts/gpt2_breakdown.py [--seq 2048] [--batch 4]
Prints one JSON line per variant plus the subtraction table.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_variant(loss_kind, vocab, seq, batch, attn="flash"):
    import jax
    import optax

    from horovod_tpu.models import get_model
    from horovod_tpu.parallel.mesh import create_mesh
    from horovod_tpu.parallel.train import lm_loss, make_train_step

    mesh = create_mesh({"dp": 1})
    spec = get_model("gpt2-small")
    kw = {"attn_impl": attn, "max_len": seq}
    if vocab is not None:
        kw["vocab_size"] = vocab
    model = spec.make_model(**kw)
    rng = np.random.RandomState(42)
    ids = rng.randint(0, vocab or 50257, size=(batch, seq), dtype=np.int32)

    if loss_kind == "xent":
        loss_fn = lm_loss
    elif loss_kind == "mean":
        def loss_fn(logits, ids):
            import jax.numpy as jnp

            return jnp.mean(logits.astype(jnp.float32))
    else:
        raise ValueError(loss_kind)

    build = make_train_step(model, optax.adamw(1e-4), loss_fn, mesh=mesh)
    init_fn, step_fn, _ = build(jax.random.PRNGKey(0), ids, ids)
    state = init_fn(jax.random.PRNGKey(0))
    return state, step_fn, ids, mesh


def time_variant(name, loss_kind, vocab, seq, batch, chunk, chunks,
                 attn="flash"):
    from bench import _make_scan_step, _step_flops, _time_scan

    state, step_fn, ids, mesh = build_variant(
        loss_kind, vocab, seq, batch, attn)
    scan_fn = _make_scan_step(step_fn, mesh, chunk)
    dt, state = _time_scan(state, scan_fn, ids, ids, chunk, chunks)
    flops = _step_flops(step_fn, state, ids, ids)
    del state, step_fn, scan_fn
    rec = {"variant": name, "ms": round(dt * 1e3, 2),
           "tflops_counted": round((flops or 0) / 1e12, 3)}
    print(json.dumps(rec), flush=True)
    return dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=12)
    ap.add_argument("--chunks", type=int, default=1)
    args = ap.parse_args()

    S, B, C, N = args.seq, args.batch, args.chunk, args.chunks
    full = time_variant("full", "xent", None, S, B, C, N)
    mean = time_variant("loss_mean", "mean", None, S, B, C, N)
    tiny = time_variant("tiny_vocab", "xent", 512, S, B, C, N)

    print(json.dumps({
        "xent_cost_ms": round((full - mean) * 1e3, 2),
        "lm_head_region_ms": round((full - tiny) * 1e3, 2),
        "full_ms": round(full * 1e3, 2),
    }), flush=True)


if __name__ == "__main__":
    main()
