#!/usr/bin/env python
"""Telemetry smoke: a real 2-worker run with the metrics endpoint live.

Validates the acceptance surface of docs/metrics.md end to end:
HOROVOD_METRICS_PORT serves Prometheus text at /metrics and per-rank
state at /status while collectives run, and hvd.metrics() reports
non-zero allreduce bytes, cycle-time histogram counts and a response
cache hit rate. Run by scripts/ci.sh; also a manual repro tool:

    python scripts/telemetry_smoke.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def worker():
    import http.client
    import json

    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    for i in range(8):
        # Same names every step: after the first negotiation these ride
        # the response-cache fast path, so the hit counter must move.
        out = np.asarray(hvd.allreduce(
            np.full(1024, float(hvd.rank() + 1), np.float32), name="smoke",
            op=hvd.Sum))
        assert float(out[0]) == 3.0, out[0]

    m = hvd.metrics()
    snap = m["metrics"]
    assert snap["horovod_allreduce_bytes_total"] > 0, snap
    assert snap["horovod_cycle_seconds"]["count"] > 0, snap
    hits = snap["horovod_response_cache_hits_total"]
    misses = snap["horovod_response_cache_misses_total"]
    assert hits > 0, (hits, misses)

    checks = {"rank": hvd.rank(), "bytes": snap["horovod_allreduce_bytes_total"],
              "cache_hit_rate": hits / max(hits + misses, 1)}
    if hvd.rank() == 0:
        # HOROVOD_METRICS_PORT=0 binds an ephemeral port (no collision
        # with concurrent CI jobs); read the actual port back from the
        # engine's exporter.
        from horovod_tpu.common import basics
        from horovod_tpu.common.metrics_export import MetricsHTTPServer

        servers = [e for e in basics.engine()._exporters
                   if isinstance(e, MetricsHTTPServer)]
        assert servers, "metrics endpoint did not start"
        conn = http.client.HTTPConnection("127.0.0.1", servers[0].port,
                                          timeout=10)
        conn.request("GET", "/metrics")
        prom = conn.getresponse().read().decode()
        assert "horovod_allreduce_bytes_total" in prom, prom[:500]
        assert "horovod_cycle_seconds_bucket" in prom, prom[:500]
        conn.request("GET", "/status")
        status = json.loads(conn.getresponse().read())
        assert status["rank"] == 0 and status["size"] == 2, status
        assert "fleet" in status, status
        # Pipelined-execution view: per-channel executor state + the
        # in-flight total the backpressure window bounds.
        assert "inflight_responses" in status, status
        assert status["channels"], status
        for ch in status["channels"].values():
            assert "queue_depth" in ch and "executing" in ch, status
        checks["status_ranks"] = sorted(int(r) for r in
                                        status["fleet"]["ranks"])
    hvd.shutdown()
    return checks


def main():
    from horovod_tpu.runner import run

    results = run(worker, np=2, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_METRICS_PORT": "0",
        "HOROVOD_METRICS_SYNC_SECONDS": "0.05",
    })
    assert len(results) == 2, results
    r0 = results[0]
    assert r0["status_ranks"] == [0, 1], r0
    print("telemetry smoke OK:", results)


if __name__ == "__main__":
    main()
