#!/usr/bin/env python
"""Telemetry smoke: real 2-worker runs with the metrics endpoint live.

Phase 1 validates the acceptance surface of docs/metrics.md end to
end: HOROVOD_METRICS_PORT serves Prometheus text at /metrics and
per-rank state at /status while collectives run, hvd.metrics() reports
non-zero allreduce bytes, cycle-time histogram counts and a response
cache hit rate — and the health plane (docs/health.md) is live:
/timeseries holds samples with derived series, /alerts lists the rule
set, and the build-info gauge is scrapable.

Phase 2 is the health-plane acceptance scenario: rank 1 arms a
deterministic `delay` fault (the chaos harness) on its own data-plane
sends, making it the persistent straggler; rank 0 polls its /alerts
endpoint until `persistent_straggler` latches FIRING with rank 1 named
in the detail, the ranks then coordinate clearing the fault over an
ordinary allreduce, and rank 0 polls until the alert RESOLVES.

Phase 3 is the goodput-plane acceptance scenario (docs/goodput.md):
the same injected straggler delay, with training demarcated by
`hvd.step()` scopes — the lost time must show up as EXPOSED-COMM
badput at /goodput (the local ledger's exposed seconds cover most of
the injected delay, the goodput ratio drops below 1, and the fleet
fold attributes per-rank exposed comm). Run by scripts/ci.sh; also a
manual repro tool:

    python scripts/telemetry_smoke.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def worker():
    import http.client
    import json

    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    for i in range(8):
        # Same names every step: after the first negotiation these ride
        # the response-cache fast path, so the hit counter must move.
        out = np.asarray(hvd.allreduce(
            np.full(1024, float(hvd.rank() + 1), np.float32), name="smoke",
            op=hvd.Sum))
        assert float(out[0]) == 3.0, out[0]

    m = hvd.metrics()
    snap = m["metrics"]
    assert snap["horovod_allreduce_bytes_total"] > 0, snap
    assert snap["horovod_cycle_seconds"]["count"] > 0, snap
    hits = snap["horovod_response_cache_hits_total"]
    misses = snap["horovod_response_cache_misses_total"]
    assert hits > 0, (hits, misses)

    checks = {"rank": hvd.rank(), "bytes": snap["horovod_allreduce_bytes_total"],
              "cache_hit_rate": hits / max(hits + misses, 1)}
    if hvd.rank() == 0:
        # HOROVOD_METRICS_PORT=0 binds an ephemeral port (no collision
        # with concurrent CI jobs); read the actual port back from the
        # engine's exporter.
        from horovod_tpu.common import basics
        from horovod_tpu.common.metrics_export import MetricsHTTPServer

        servers = [e for e in basics.engine()._exporters
                   if isinstance(e, MetricsHTTPServer)]
        assert servers, "metrics endpoint did not start"
        conn = http.client.HTTPConnection("127.0.0.1", servers[0].port,
                                          timeout=10)
        conn.request("GET", "/metrics")
        prom = conn.getresponse().read().decode()
        assert "horovod_allreduce_bytes_total" in prom, prom[:500]
        assert "horovod_cycle_seconds_bucket" in prom, prom[:500]
        # Build identity rides the default registry (docs/health.md).
        assert "horovod_build_info" in prom, prom[:500]
        assert "horovod_uptime_seconds" in prom, prom[:500]
        # Health plane: /timeseries serves the sampler ring (wait for
        # the first tick) with derived series; /alerts serves the rule
        # table with no false positives on a healthy mesh.
        import time as _time

        deadline = _time.monotonic() + 15
        tsbody = {}
        while _time.monotonic() < deadline:
            conn.request("GET", "/timeseries")
            tsbody = json.loads(conn.getresponse().read())
            if tsbody.get("depth", 0) >= 2 and \
                    "horovod_cycle_seconds" in tsbody.get("derived", {}):
                break
            _time.sleep(0.1)
        assert tsbody.get("depth", 0) >= 2, tsbody
        assert "horovod_allreduce_bytes_total" in tsbody["derived"], \
            sorted(tsbody["derived"])[:10]
        conn.request("GET", "/alerts")
        alerts = json.loads(conn.getresponse().read())
        assert "persistent_straggler" in alerts["local"]["rules"], alerts
        assert alerts["local"]["firing"] == [], alerts
        assert "fleet" in alerts, alerts
        conn.request("GET", "/status")
        status = json.loads(conn.getresponse().read())
        assert "timeseries" in status and "alerts" in status, \
            sorted(status)
        assert status["rank"] == 0 and status["size"] == 2, status
        assert "fleet" in status, status
        # Pipelined-execution view: per-channel executor state + the
        # in-flight total the backpressure window bounds.
        assert "inflight_responses" in status, status
        assert status["channels"], status
        for ch in status["channels"].values():
            assert "queue_depth" in ch and "executing" in ch, status
        checks["status_ranks"] = sorted(int(r) for r in
                                        status["fleet"]["ranks"])
    hvd.shutdown()
    return checks


def worker_straggler():
    """Health-plane acceptance: rank 1 arms a delay fault on its own
    sends (it becomes the straggler every negotiation); rank 0 watches
    /alerts until the rank-attributed fire, the ranks coordinate the
    clear over an allreduce, and rank 0 watches until the resolve."""
    import http.client
    import json
    import time

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.common import basics, fault_injection
    from horovod_tpu.common.fault_injection import Rule
    from horovod_tpu.common.metrics_export import MetricsHTTPServer

    hvd.init()
    r = hvd.rank()
    if r == 1:
        # Installed only in THIS process — every send rank 1 makes is
        # late, so the coordinator's straggler gauge pins to 1.
        fault_injection.injector.install(
            [Rule(action="delay", peer=0, op="send", secs=0.03)])

    port = None
    if r == 0:
        servers = [e for e in basics.engine()._exporters
                   if isinstance(e, MetricsHTTPServer)]
        assert servers, "metrics endpoint did not start"
        port = servers[0].port

    def alerts_body():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/alerts")
        return json.loads(conn.getresponse().read())

    phase = 0  # 0: waiting for fire, 1: waiting for resolve, 2: done
    detail = None
    cleared = False
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        # Keep collectives flowing (the straggler gauge and the
        # activity guard both need live negotiations).
        hvd.allreduce(np.ones(256, np.float32), name="work")
        if r == 0:
            body = alerts_body()
            firing = body["local"]["firing"]
            if phase == 0 and "persistent_straggler" in firing:
                detail = body["local"]["rules"][
                    "persistent_straggler"]["detail"]
                assert detail["rank"] == 1, detail
                phase = 1
            elif phase == 1 and "persistent_straggler" not in firing:
                phase = 2
        # Phase word: rank 0 contributes the phase, rank 1 zero, so the
        # sum IS rank 0's phase on every rank — the clear coordination.
        sig = np.asarray(hvd.allreduce(
            np.full(1, float(phase if r == 0 else 0), np.float32),
            name="phase", op=hvd.Sum))
        if r == 1 and sig[0] >= 1 and not cleared:
            fault_injection.injector.clear()
            cleared = True
        if sig[0] >= 2:
            break
        time.sleep(0.02)
    checks = {"rank": r, "phase": phase, "detail": detail,
              "cleared": cleared}
    if r == 0:
        assert phase == 2, (
            "straggler alert never completed fire->resolve", checks)
        # Lifecycle journal (docs/events.md): the fire->resolve cycle
        # must have landed in the events plane too, fire before clear,
        # both attributed to the rule — and the /events view serves it.
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/events")
        events_view = json.loads(conn.getresponse().read())
        assert events_view["local"]["enabled"], events_view
        evs = [d for d in events_view["local"]["events"]
               if (d.get("attrs") or {}).get("rule")
               == "persistent_straggler"]
        kinds = [d["kind"] for d in evs]
        assert "alert.fire" in kinds and "alert.clear" in kinds, kinds
        assert kinds.index("alert.fire") < kinds.index("alert.clear"), \
            kinds
        fire = evs[kinds.index("alert.fire")]
        assert fire["sev"] == "warn" and fire["rank"] == 0, fire
        checks["alert_events"] = kinds
    hvd.shutdown()
    return checks


def worker_goodput():
    """Goodput-plane acceptance: rank 1 delays every data-plane send by
    DELAY_S, so each demarcated step's collective blocks the training
    thread — exposed communication. Rank 0 asserts the /goodput view
    attributes the lost time to the exposed-comm badput bucket."""
    import http.client
    import json
    import time

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.common import basics, fault_injection
    from horovod_tpu.common.fault_injection import Rule
    from horovod_tpu.common.metrics_export import MetricsHTTPServer

    DELAY_S = 0.05
    STEPS = 12
    hvd.init()
    r = hvd.rank()
    if r == 1:
        fault_injection.injector.install(
            [Rule(action="delay", peer=0, op="send", secs=DELAY_S)])

    for _ in range(STEPS):
        # The demarcation under test: each step scope brackets one
        # synchronous allreduce whose handle wait absorbs the delay.
        with hvd.step():
            hvd.allreduce(np.ones(1024, np.float32), name="gstep")

    led = basics.engine().goodput
    local = led.view()
    checks = {"rank": r,
              "steps": local["steps"]["total"],
              "exposed_s": local["badput"]["exposed_comm_seconds"],
              "ratio": local["goodput"]["ratio"]}
    assert local["steps"]["total"] == STEPS, local["steps"]
    # Every step blocked ~DELAY_S on the straggler: the ledger must
    # attribute the bulk of the injected delay as exposed comm.
    floor = 0.5 * DELAY_S * STEPS
    assert local["badput"]["exposed_comm_seconds"] > floor, local
    assert local["goodput"]["ratio"] is not None, local
    assert local["goodput"]["ratio"] < 0.9, local

    if r == 0:
        servers = [e for e in basics.engine()._exporters
                   if isinstance(e, MetricsHTTPServer)]
        assert servers, "metrics endpoint did not start"
        port = servers[0].port

        def goodput_body():
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=10)
            conn.request("GET", "/goodput")
            return json.loads(conn.getresponse().read())

        # The fleet fold needs rank 1's piggybacked scalars; keep
        # collectives flowing until both ranks appear (phase word
        # below holds rank 1 in the loop meanwhile).
        deadline = time.monotonic() + 60
        body = goodput_body()
        while time.monotonic() < deadline:
            fleet = body.get("fleet", {}).get("ranks", {})
            if ("0" in fleet and "1" in fleet
                    and fleet["0"]["exposed_comm_seconds"] > 0
                    and fleet["1"]["steps"] >= STEPS):
                break
            time.sleep(0.1)
            body = goodput_body()
        fleet = body.get("fleet", {}).get("ranks", {})
        assert "0" in fleet and "1" in fleet, body
        assert fleet["0"]["exposed_comm_seconds"] > floor, body
        assert body["local"]["badput"]["exposed_comm_seconds"] > floor, \
            body
        assert "max_exposed_comm_rank" in body["fleet"], body
        # /status carries the compact goodput section too.
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/status")
        status = json.loads(conn.getresponse().read())
        assert "goodput" in status, sorted(status)
        assert status["goodput"]["steps"] >= STEPS, status["goodput"]
        checks["fleet_ranks"] = sorted(fleet)

    # Coordinated exit: rank 0 signals it is done asserting, so rank 1
    # keeps answering the fleet-refresh collectives until then.
    done = 0
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        sig = np.asarray(hvd.allreduce(
            np.full(1, float(1 if r == 0 else 0), np.float32),
            name="gp_done", op=hvd.Sum))
        if sig[0] >= 1:
            done = 1
            break
        time.sleep(0.02)
    assert done == 1, "goodput phase never converged"
    hvd.shutdown()
    return checks


def main():
    from horovod_tpu.runner import run

    results = run(worker, np=2, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_METRICS_PORT": "0",
        "HOROVOD_METRICS_SYNC_SECONDS": "0.05",
        "HOROVOD_METRICS_SAMPLE_SECONDS": "0.2",
    })
    assert len(results) == 2, results
    r0 = results[0]
    assert r0["status_ranks"] == [0, 1], r0
    print("telemetry smoke OK (phase 1):", results)

    # Phase 2: the injected-straggler fire -> attribute -> resolve
    # round-trip. Fast sampler + a smoke-scaled rule override (the
    # production default needs 90% dominance over 10 samples held 30s).
    results = run(worker_straggler, np=2, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_METRICS_PORT": "0",
        "HOROVOD_METRICS_SYNC_SECONDS": "0.05",
        "HOROVOD_METRICS_SAMPLE_SECONDS": "0.2",
        "HOROVOD_ALERT_RULES":
            "persistent_straggler:k=4:n=5:for_seconds=0.3",
    })
    assert len(results) == 2, results
    assert results[0]["phase"] == 2, results
    assert results[0]["detail"]["rank"] == 1, results
    assert results[1]["cleared"], results
    print("telemetry smoke OK (phase 2, straggler fire/resolve):",
          results)

    # Phase 3: the injected straggler delay must land in the goodput
    # ledger's exposed-comm badput bucket, attributed at /goodput
    # (docs/goodput.md).
    results = run(worker_goodput, np=2, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_METRICS_PORT": "0",
        "HOROVOD_METRICS_SYNC_SECONDS": "0.05",
        "HOROVOD_METRICS_SAMPLE_SECONDS": "0.2",
    })
    assert len(results) == 2, results
    r0 = results[0]
    assert r0["fleet_ranks"] == ["0", "1"], results
    assert r0["exposed_s"] > 0 and r0["ratio"] < 0.9, results
    print("telemetry smoke OK (phase 3, exposed-comm badput at "
          "/goodput):", results)


if __name__ == "__main__":
    main()
