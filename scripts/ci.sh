#!/usr/bin/env bash
# Minimal CI pipeline (ref: .buildkite/gen-pipeline.sh:10-27 runs the
# test suite across framework combos; this single-node variant runs the
# full suite, the multichip sharding dryrun, and a CPU bench smoke).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== native core build (cc/libhvdtpu.so — docs/native.md) ==="
# Build up front so every stage below exercises the C++ kernels; a
# build failure is a CI failure, not a silent numpy fallback.
make -C horovod_tpu/cc -s
python - <<'EOF'
from horovod_tpu.cc import native
st = native.status()
assert st["loaded"], f"native core built but failed to load: {st}"
print(f"native core loaded: abi {st['abi']}, {st['threads']} threads, "
      f"{sum(st['kernels'].values())}/{len(st['kernels'])} kernels")
EOF

echo "=== engine/transport subset, native kernels ON ==="
ENGINE_SUBSET="tests/test_native.py tests/test_engine.py tests/test_ring.py \
  tests/test_transport.py tests/test_hierarchical.py tests/test_compression.py"
python -m pytest $ENGINE_SUBSET -q -m 'not slow'

echo "=== engine/transport subset, HOROVOD_DISABLE_NATIVE=1 (numpy fallback parity) ==="
HOROVOD_DISABLE_NATIVE=1 python -m pytest $ENGINE_SUBSET -q -m 'not slow'

echo "=== unit + integration tests (fast tier — FULLY GREEN tier-1) ==="
# The 7 known jax<0.5 failures (gpipe x2 + pipelined-lm, flash-GSPMD x2,
# bert-ring-mask, elastic-gspmd-traced) were fixed by the
# partial-manual shard_map compat shims (utils/compat.py); tier-1 is
# asserted fully green — ANY failed test fails CI, no known-failure
# allowance remains.
if ! python -m pytest tests/ -q -m 'not slow'; then
  echo "tier-1 is no longer fully green"
  exit 1
fi

echo "=== slow tier (full adapter / chaos coverage) ==="
python -m pytest tests/ -x -q -m slow

echo "=== telemetry smoke (metrics endpoint + snapshot + health plane: /timeseries, /alerts, straggler fire/resolve) ==="
python scripts/telemetry_smoke.py

echo "=== tracing smoke (merged /trace + post-mortem on injected sever) ==="
python scripts/trace_smoke.py

echo "=== data-plane perf smoke (tcp + shm + hierarchical, exact byte accounting per transport) ==="
python scripts/perf_smoke.py

echo "=== ZeRO perf smoke (np=4 sharded optimizer: exact gradient-allreduce + segment-allgather byte accounting, bitwise parity vs replicated) ==="
python scripts/perf_smoke.py zero

echo "=== chaos smoke over shared memory (wedge detection while data rides shm) ==="
python scripts/chaos_smoke.py --transport shm --wedge

echo "=== elastic recovery smoke (wedge 1 of 4, survivors resume at np=3) ==="
python scripts/elastic_smoke.py

echo "=== preemption smoke (announced drain: zero lost steps, preemption-bucket attribution, graceful beats timeout goodput) ==="
python scripts/preemption_smoke.py

echo "=== durability smoke (kill ALL ranks, restart, bitwise resume) ==="
python scripts/checkpoint_smoke.py

echo "=== checkpoint overhead smoke (background write <5% of step time) ==="
python scripts/checkpoint_smoke.py --overhead

echo "=== serving smoke (4-rank continuous batching: p50/p99 under concurrent load, weight hot-swap mid-traffic, wedged-replica eviction) ==="
python scripts/serving_smoke.py

echo "=== perf report (warn vs committed BENCH_BASELINE.json; docs/health.md) ==="
python scripts/perf_report.py --quick --out /tmp/hvd_perf1.json

# Resume the BENCH trajectory (empty since r05): archive this run's
# perf report as the next BENCH_r<NN>.json next to BENCH_BASELINE.json.
last=$( (ls BENCH_r[0-9]*.json 2>/dev/null || true) \
  | sed -E 's/.*BENCH_r0*([0-9]+)\.json/\1/' | sort -n | tail -1)
next=$(( ${last:-0} + 1 ))
cp /tmp/hvd_perf1.json "$(printf 'BENCH_r%02d.json' "$next")"
echo "BENCH trajectory: archived $(printf 'BENCH_r%02d.json' "$next")"

echo "=== perf gate self-test (clean back-to-back must pass; injected 2x slowdown must trip) ==="
python scripts/perf_report.py --quick --out /tmp/hvd_perf2.json \
    --baseline /tmp/hvd_perf1.json --gate
if python scripts/perf_report.py --replay /tmp/hvd_perf2.json \
    --baseline /tmp/hvd_perf1.json --inject-slowdown 2.0 --gate; then
  echo "perf gate FAILED TO TRIP on an injected 2x slowdown"
  exit 1
fi

echo "=== multichip sharding dryrun (8 virtual devices) ==="
python __graft_entry__.py

echo "=== bench smoke (CPU) ==="
python bench.py --cpu --no-scaling

echo "CI OK"
