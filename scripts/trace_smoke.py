#!/usr/bin/env python
"""Tracing-plane smoke: a real 2-worker run exercising the acceptance
surface of docs/tracing.md end to end.

Part 1 — merged trace: with the metrics endpoint live, rank 0's /trace
must serve a Chrome/Perfetto document whose X events cover BOTH ranks
(one process lane each) and whose executor spans share trace ids across
ranks per collective (the wire-carried correlation id).

Part 2 — failure post-mortem: re-run with an injected sever
(HOROVOD_FAULT_INJECT) and HOROVOD_TRACE_DIR set; every rank must dump
its flight recorder on the engine latch and the coordinator must stitch
them into postmortem.json naming the severed peer.

Run by scripts/ci.sh; also a manual repro tool:

    python scripts/trace_smoke.py
"""
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

TRACE_DIR = os.environ.get("TRACE_SMOKE_DIR")  # set by main() for workers


def worker_merged():
    import http.client
    import time

    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    for i in range(10):
        out = np.asarray(hvd.allreduce(
            np.full(512, float(hvd.rank() + 1), np.float32),
            name=f"smoke{i % 4}", op=hvd.Sum))
        assert float(out[0]) == 3.0, out[0]
        time.sleep(0.02)
    # One more synced round so the final span batches ride a gather.
    time.sleep(0.2)
    np.asarray(hvd.allreduce(np.ones(8, np.float32), name="fin", op=hvd.Sum))

    result = {"rank": hvd.rank()}
    if hvd.rank() == 0:
        from horovod_tpu.common import basics
        from horovod_tpu.common.metrics_export import MetricsHTTPServer

        servers = [e for e in basics.engine()._exporters
                   if isinstance(e, MetricsHTTPServer)]
        assert servers, "metrics endpoint did not start"
        conn = http.client.HTTPConnection("127.0.0.1", servers[0].port,
                                          timeout=10)
        conn.request("GET", "/trace")
        doc = json.loads(conn.getresponse().read())
        evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        pids = {e["pid"] for e in evs}
        assert pids >= {0, 1}, f"merged trace missing rank lanes: {pids}"
        ids = {p: {e["args"]["trace_id"] for e in evs
                   if e["pid"] == p and str(e["name"]).startswith("exec.")
                   and e["args"]["trace_id"]}
               for p in (0, 1)}
        shared = ids[0] & ids[1]
        assert len(shared) >= 3, (
            f"collectives must share trace ids across ranks: "
            f"rank0={len(ids[0])} rank1={len(ids[1])} shared={len(shared)}")
        # /status trace view: recorder live, spans collected from both.
        conn.request("GET", "/status")
        status = json.loads(conn.getresponse().read())
        tr = status["trace"]
        assert tr["enabled"] and tr["depth"] > 0, tr
        assert set(tr["collected"]) >= {"0", "1"}, tr
        result.update(shared_ids=len(shared),
                      lanes=sorted(int(p) for p in pids))
    hvd.shutdown()
    return result


def worker_postmortem():
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.common.exceptions import HorovodInternalError

    hvd.init()
    err = None
    try:
        for i in range(50):
            np.asarray(hvd.allreduce(
                np.full(256, 1.0, np.float32), name=f"pm{i}", op=hvd.Sum))
    except HorovodInternalError as e:
        err = str(e)
    assert err is not None, "injected sever never surfaced"
    rank = hvd.rank()
    # Engine teardown (dump + rank-0 stitch) runs on the background
    # thread; shutdown() joins it.
    hvd.shutdown()
    return {"rank": rank, "error": err}


def main():
    from horovod_tpu.runner import run

    # -- part 1: merged /trace ------------------------------------------
    results = run(worker_merged, np=2, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_METRICS_PORT": "0",
        "HOROVOD_METRICS_SYNC_SECONDS": "0.05",
        "HOROVOD_HEARTBEAT_INTERVAL_SECONDS": "0.2",
    })
    r0 = next(r for r in results if r["rank"] == 0)
    assert r0["shared_ids"] >= 3 and r0["lanes"][:2] == [0, 1], r0
    print(f"trace smoke part 1 OK: lanes={r0['lanes']} "
          f"shared trace ids={r0['shared_ids']}")

    # -- part 2: injected sever -> stitched post-mortem -----------------
    trace_dir = tempfile.mkdtemp(prefix="hvd_trace_pm_")
    try:
        results = run(worker_postmortem, np=2, extra_env={
            "JAX_PLATFORMS": "cpu",
            "HOROVOD_CYCLE_TIME": "1",
            "HOROVOD_TRACE_DIR": trace_dir,
            "HOROVOD_METRICS_SYNC_SECONDS": "0.05",
            # rank 1 severs its link to the coordinator after 40 frames:
            # both engines die with an attributed error.
            "HOROVOD_FAULT_INJECT": "sever:rank=1:peer=0:after=40",
        })
        for r in results:
            assert r["error"], r
        flights = sorted(f for f in os.listdir(trace_dir)
                         if f.startswith("flight_rank"))
        assert len(flights) == 2, (flights, os.listdir(trace_dir))
        pm_path = os.path.join(trace_dir, "postmortem.json")
        assert os.path.exists(pm_path), os.listdir(trace_dir)
        pm = json.load(open(pm_path))
        meta = pm["horovod_postmortem"]
        assert meta["ranks"] == [0, 1], meta
        # The stitched verdict names the severed peer (rank 1 <-> 0).
        blob = json.dumps(meta)
        assert "peer" in blob or "rank 1" in blob, meta
        evs = [e for e in pm["traceEvents"] if e.get("ph") == "X"]
        assert {e["pid"] for e in evs} >= {0, 1}, "post-mortem missing lanes"
        print(f"trace smoke part 2 OK: {len(flights)} flight dumps, "
              f"postmortem verdict={meta['verdict'][:80]!r}")
    finally:
        shutil.rmtree(trace_dir, ignore_errors=True)

    print("trace smoke OK")


if __name__ == "__main__":
    main()
