#!/usr/bin/env python
"""Elastic-recovery smoke: wedge 1 of 4 elastic workers, assert the
survivors resume at np=3 within the deadline.

The CI-runnable version of the liveness-plane acceptance scenario
(tests/test_health.py::test_chaos_wedge_elastic_recovery_and_hang_control,
minus the hang control): four local workers under a real ElasticDriver,
``HOROVOD_TCP_TIMEOUT_SECONDS=0`` (unbounded), one worker FREEZES
mid-step (``wedge`` fault rule: process alive, sockets open, heartbeats
stop). The heartbeat plane must declare it dead, the driver must evict
its slot at the ready deadline and blacklist its host, and the three
survivors must finish training at np=3 — all inside ``--deadline``
seconds.

    python scripts/elastic_smoke.py
    python scripts/elastic_smoke.py --wedge-host hostA --deadline 180
"""
from __future__ import annotations

import argparse
import importlib.util
import os
import pickle
import sys
import tempfile
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _incident_report():
    spec = importlib.util.spec_from_file_location(
        "incident_report",
        os.path.join(REPO, "scripts", "incident_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ordered(kinds, *want) -> bool:
    """True when `want` appears as an ordered subsequence of kinds."""
    i = 0
    for w in want:
        try:
            i = kinds.index(w, i) + 1
        except ValueError:
            return False
    return True

WORKER = textwrap.dedent("""
    import os, pickle, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.backend.elastic_env import spawn_identity
    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.common import fault_injection
    from horovod_tpu.elastic.state import ObjectState
    from horovod_tpu.utils import env as env_cfg

    TOTAL = int(os.environ["SMOKE_TOTAL_BATCHES"])
    hvd.init()
    state = ObjectState(batch=0, history=[])

    @hvd.elastic.run
    def train(state):
        while state.batch < TOTAL:
            hvd.allreduce(np.ones(2, np.float32), name="g")
            fault_injection.advance_step()   # the doomed worker wedges here
            state.history.append((hvd.rank(), hvd.size()))
            state.batch += 1
            state.commit()
            time.sleep(0.05)
        return list(state.history)

    hist = train(state)
    # Goodput plane (docs/goodput.md): the eviction's disruption window
    # (failure -> re-meshed training) must have landed in the ledger's
    # restart-badput bucket on every survivor.
    from horovod_tpu.common import goodput
    gp = goodput.active().view()
    rdv = RendezvousClient(env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR),
                           env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0))
    rdv.put("smoke_results", spawn_identity(),
            pickle.dumps({"hist": hist, "goodput": gp}))
    print(f"worker {spawn_identity()} done as rank {hvd.rank()} "
          f"size {hvd.size()}", flush=True)
""")

HOSTS = ["hostA", "hostB", "hostC", "hostD"]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--wedge-host", default="hostC",
                    help="logical host whose worker wedges (default hostC)")
    ap.add_argument("--wedge-step", type=int, default=3)
    ap.add_argument("--batches", type=int, default=12)
    ap.add_argument("--deadline", type=float, default=240.0,
                    help="wall-clock bound on the whole scenario")
    ap.add_argument("--hb-interval", type=float, default=0.5)
    ap.add_argument("--hb-miss", type=int, default=4)
    ap.add_argument("--ready-timeout", type=float, default=8.0,
                    help="HOROVOD_ELASTIC_READY_TIMEOUT for the driver")
    args = ap.parse_args()

    from horovod_tpu.common import events as events_mod
    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.launch import slot_env, spawn_worker
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    os.environ["HVDRUN_FORCE_LOCAL"] = "1"
    os.environ["HOROVOD_ELASTIC_READY_TIMEOUT"] = str(args.ready_timeout)
    events_dir = tempfile.mkdtemp(prefix="hvd_events_")
    # The driver journals lifecycle events as rank -1
    # (events_driver.jsonl); workers get the dir via env below.
    events_mod.set_current(events_mod.EventRecorder(
        rank=-1, spool_dir=events_dir, spool_seconds=0.1))
    server = RendezvousServer()
    port = server.start()
    driver = ElasticDriver(server, FixedHosts({h: 1 for h in HOSTS}),
                           min_np=2, max_np=4, poll_interval=0.25)

    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "worker.py")
        with open(script, "w") as f:
            f.write(WORKER)

        def create_worker(slot, extra_env):
            env = slot_env(slot, "127.0.0.1", port, elastic=True)
            env.update(extra_env)
            env["PYTHONPATH"] = REPO
            env["HVDRUN_FORCE_LOCAL"] = "1"
            env["HOROVOD_CYCLE_TIME"] = "1"
            env["HOROVOD_TCP_TIMEOUT_SECONDS"] = "0"   # unbounded: the point
            env["HOROVOD_HEARTBEAT_INTERVAL_SECONDS"] = str(args.hb_interval)
            env["HOROVOD_HEARTBEAT_MISS_LIMIT"] = str(args.hb_miss)
            env["SMOKE_TOTAL_BATCHES"] = str(args.batches)
            env["HOROVOD_EVENTS_DIR"] = events_dir
            env["HOROVOD_EVENTS_SPOOL_SECONDS"] = "0.1"
            env.pop("HOROVOD_FAULT_INJECT", None)
            if slot.hostname == args.wedge_host:
                env["HOROVOD_FAULT_INJECT"] = f"wedge:step={args.wedge_step}"
            handle = spawn_worker(slot, [sys.executable, script], env,
                                  prefix_output=False)
            return handle.proc

        t0 = time.monotonic()
        try:
            driver.start(create_worker)
            code = driver.wait(timeout=args.deadline)
            elapsed = time.monotonic() - t0
            if code != 0:
                print(f"FAIL: driver exit {code} after {elapsed:.0f}s "
                      f"(None = still hung at the deadline)", flush=True)
                return 1
            survivors = [h for h in HOSTS if h != args.wedge_host]
            ok = True
            for h in survivors:
                blob = server.handle_get(f"smoke_results/{h}:0")
                if blob is None:
                    print(f"FAIL: survivor {h} reported no result",
                          flush=True)
                    ok = False
                    continue
                doc = pickle.loads(blob)
                hist, gp = doc["hist"], doc["goodput"]
                final_np = hist[-1][1]
                downtime = gp["badput"]["restart_downtime_seconds"]
                ratio = gp["goodput"]["ratio"]
                print(f"{h}: finished batch {len(hist)} at np={final_np}, "
                      f"restart badput {downtime:.2f}s "
                      f"(goodput ratio "
                      f"{'none' if ratio is None else format(ratio, '.3f')})",
                      flush=True)
                ok = ok and final_np == 3
                # The eviction cost real wall time (detection + barrier
                # + re-mesh); it must be attributed, not lost.
                if downtime <= 0:
                    print(f"FAIL: survivor {h} recorded no restart-"
                          "badput for the eviction", flush=True)
                    ok = False
                if not (gp["goodput"]["ratio"] is not None
                        and gp["goodput"]["ratio"] < 1.0):
                    print(f"FAIL: survivor {h} goodput ratio not < 1",
                          flush=True)
                    ok = False
            if not driver.host_manager.blacklist_strikes(args.wedge_host):
                print(f"FAIL: wedged host {args.wedge_host} was never "
                      "blacklisted", flush=True)
                ok = False
            # The lifecycle chronicle (docs/events.md): merging every
            # journal must read the wedge as one causal narrative.
            events_mod.active().flush_spool()
            report = _incident_report().build_report([events_dir])
            kinds = [d["kind"] for d in report["events"]]
            print(f"chronicle: {len(kinds)} events from ranks "
                  f"{report['summary']['ranks']}", flush=True)
            # Survivors restore/reset under the OLD epoch (the failed
            # collective) before the driver's new-epoch remesh — the
            # causal sort orders the wedge exactly that way.
            if not _ordered(kinds, "health.verdict", "elastic.evict",
                            "elastic.restore", "elastic.reset",
                            "elastic.remesh"):
                print("FAIL: chronicle lost the wedge narrative "
                      "(verdict -> evict -> restore -> reset -> "
                      f"remesh): {kinds}", flush=True)
                ok = False
            if not _ordered(kinds, "elastic.evict", "host.blacklist"):
                print("FAIL: chronicle lost the strike order "
                      f"(evict -> blacklist): {kinds}", flush=True)
                ok = False
            print(f"recovered and finished at np=3 in {elapsed:.0f}s "
                  f"(deadline {args.deadline:.0f}s)" if ok else "FAIL",
                  flush=True)
            print("PASS" if ok else "FAIL", flush=True)
            return 0 if ok else 1
        finally:
            driver.stop()
            server.stop()
            import shutil

            shutil.rmtree(events_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
