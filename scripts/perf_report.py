#!/usr/bin/env python
"""Standardized perf report + CI regression gate (docs/health.md).

One harness that runs the repo's microbench stages — small-op latency,
ring / segmented-ring bandwidth, the tcp-vs-shm transport pair, the
two-level hierarchical allreduce, the 16MB reduce-scatter leg, the
np=4 ZeRO-1 optimizer step (plus its measured per-rank state bytes),
and a serving round-trip — and emits
a BENCH-style JSON: medians over order-alternated rounds (the house
methodology from the PR 3/4/8 acceptance measurements: on a shared box,
sequential arms measure load drift, so stage order alternates per
round and the median of rounds is the stage value). The report stamps
``horovod_build_info`` (version + jax) so every number is attributable
to a build — the BENCH trajectory stopped being recorded after PR 5;
this file is how it restarts.

Comparison: every stage is lower-is-better; a stage regresses when
``value / baseline > 1 + tolerance`` (strictly — the boundary passes).
Tolerances are per-stage (the baseline file may carry a
``tolerances`` map) with a generous default, because CI boxes are
noisy and a flaky gate is worse than none.

CI wiring (scripts/ci.sh): warn-by-default against the committed
``BENCH_BASELINE.json``; gating is the explicit opt-in (``--gate``).
The gate itself is proven live on every CI run: a clean back-to-back
run must pass, and a ``--replay --inject-slowdown 2.0`` of the same
measurements must trip it.

    python scripts/perf_report.py                         # measure, warn
    python scripts/perf_report.py --gate                  # measure, gate
    python scripts/perf_report.py --update-baseline       # refresh baseline
    python scripts/perf_report.py --replay r.json --baseline b.json \
        --inject-slowdown 2.0 --gate                      # gate self-test
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "BENCH_BASELINE.json")
DEFAULT_TOLERANCE = 0.5

SCHEMA = 1


def _median(vals):
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return float("nan")
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _quantile(sorted_vals, q):
    return sorted_vals[min(int(q * len(sorted_vals)),
                           len(sorted_vals) - 1)]


# ---------------------------------------------------------------------------
# Measurement workers (run under the process-mode launcher, like
# perf_smoke). Each returns {stage: seconds} for ONE round; main()
# aggregates rounds into medians.

def _engine_worker():
    """np=2 engine stages: latency / ring / segring / transport, in
    per-round alternating order."""
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.common import basics

    hvd.init()
    eng = basics.engine()
    rounds = int(os.environ["PERF_ROUNDS"])
    lat_iters = int(os.environ["PERF_LAT_ITERS"])
    bw_iters = int(os.environ["PERF_BW_ITERS"])
    tr_iters = int(os.environ["PERF_TR_ITERS"])
    lat_x = np.ones(16384, np.float32)     # 64KB
    bw_x = np.ones(262144, np.float32)     # 1MB
    tr_x = np.ones(1048576, np.float32)    # 4MB
    cmp_x = np.ones(4194304, np.float32)   # 16MB

    def set_algo(ring: bool, seg_bytes: int):
        os.environ.pop("HOROVOD_CPU_OPERATIONS", None)
        os.environ["HOROVOD_RING_THRESHOLD"] = "0" if ring else str(1 << 40)
        os.environ["HOROVOD_RING_SEGMENT_BYTES"] = str(seg_bytes)

    def stage_latency(tag):
        set_algo(False, 0)
        name = "pr.lat"
        for _ in range(3):
            eng.synchronize(eng.enqueue_allreduce(lat_x, name=name),
                            timeout=120)
        hvd.barrier()
        lats = []
        for _ in range(lat_iters):
            t0 = time.perf_counter()
            eng.synchronize(eng.enqueue_allreduce(lat_x, name=name),
                            timeout=120)
            lats.append(time.perf_counter() - t0)
        hvd.barrier()
        lats.sort()
        return _quantile(lats, 0.5)

    def _timed_allreduce(x, name, iters):
        hvd.barrier()
        t0 = time.perf_counter()
        for _ in range(iters):
            hvd.allreduce(x, name=name, op=hvd.Sum)
        dt = (time.perf_counter() - t0) / iters
        hvd.barrier()
        return dt

    def stage_ring(tag):
        set_algo(True, 0)
        return _timed_allreduce(bw_x, "pr.ring", bw_iters)

    def stage_segring(tag):
        set_algo(True, 1 << 18)
        return _timed_allreduce(bw_x, "pr.segring", bw_iters)

    def stage_transport(tag):
        """tcp-vs-shm paired inside the stage (order alternates with
        the round parity, the PR 8 protocol)."""
        set_algo(True, 1 << 18)

        def arm(transport):
            os.environ["HOROVOD_TRANSPORT"] = transport
            return _timed_allreduce(tr_x, f"pr.tr.{transport}", tr_iters)

        if tag % 2 == 0:
            tcp = arm("tcp")
            shm = arm("shm")
        else:
            shm = arm("shm")
            tcp = arm("tcp")
        os.environ["HOROVOD_TRANSPORT"] = "auto"
        return {"tcp": tcp, "shm": shm}

    def stage_compression(tag):
        """none-vs-bf16 paired inside the stage at 16MB (order
        alternates with the round parity, like the transport stage).
        Per-arm steady-state names: the codec id is negotiated once
        per name and replays from the response cache."""
        set_algo(True, 1 << 18)
        os.environ["HOROVOD_WIRE_COMPRESSION_MIN_BYTES"] = "0"

        def arm(mode):
            os.environ["HOROVOD_WIRE_COMPRESSION"] = mode
            return _timed_allreduce(cmp_x, f"pr.cmp.{mode}", tr_iters)

        if tag % 2 == 0:
            none = arm("none")
            bf16 = arm("bf16")
        else:
            bf16 = arm("bf16")
            none = arm("none")
        os.environ["HOROVOD_WIRE_COMPRESSION"] = "none"
        return {"none": none, "bf16": bf16}

    def stage_native(tag):
        """native-vs-fallback paired at 16MB over the segmented ring
        (order alternates with the round parity): the C++ kernel-port
        A/B (docs/native.md). HOROVOD_DISABLE_NATIVE is honored per
        call by cc/native.py, so flipping the env between arms flips
        the data plane live — no reload dance."""
        set_algo(True, 1 << 18)

        def arm(disabled):
            if disabled:
                os.environ["HOROVOD_DISABLE_NATIVE"] = "1"
            else:
                os.environ.pop("HOROVOD_DISABLE_NATIVE", None)
            name = "pr.nat.off" if disabled else "pr.nat.on"
            return _timed_allreduce(cmp_x, name, tr_iters)

        if tag % 2 == 0:
            on = arm(False)
            off = arm(True)
        else:
            off = arm(True)
            on = arm(False)
        os.environ.pop("HOROVOD_DISABLE_NATIVE", None)
        return {"on": on, "off": off}

    def stage_reducescatter(tag):
        """16MB reduce-scatter over the segmented ring: each rank
        leaves with its 1/n slice of the summed dim 0 — the ZeRO
        gradient leg (docs/running.md "ZeRO sharded optimizer state").
        The steady `pr.rs` name keeps the inner reduction on the
        response cache, so this tracks the cached-path cost
        head-to-head with the 16MB allreduce stages above."""
        set_algo(True, 1 << 18)
        hvd.barrier()
        t0 = time.perf_counter()
        for _ in range(tr_iters):
            hvd.reducescatter(cmp_x, op=hvd.Sum, name="pr.rs")
        dt = (time.perf_counter() - t0) / tr_iters
        hvd.barrier()
        return dt

    stages = [
        ("latency_small_p50_s", stage_latency),
        ("ring_1mb_s", stage_ring),
        ("segring_1mb_s", stage_segring),
        ("transport_4mb_s", stage_transport),
        ("compression_16mb_s", stage_compression),
        ("native_ring_16mb_s", stage_native),
        ("reducescatter_16mb_s", stage_reducescatter),
    ]
    out = {name: [] for name, _ in stages}
    # Warmup round (negotiation, cache fill, shm establishment) —
    # discarded.
    for name, fn in stages:
        fn(0)
    for r in range(rounds):
        order = stages if r % 2 == 0 else list(reversed(stages))
        for name, fn in order:
            out[name].append(fn(r))
    rank = hvd.rank()
    hvd.shutdown()
    return {"rank": rank, "stages": out}


def _hier_worker():
    """np=4 simulated 2-host x 2-slot hierarchical allreduce: the 1MB
    auto-mode stage (tracking whatever the defaults resolve to) plus
    `hier_arena_16mb` — the tentpole shape, 16MB fp32 leader mode with
    the per-host arena intra-host legs pinned on."""
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    rounds = int(os.environ["PERF_ROUNDS"])
    iters = int(os.environ["PERF_BW_ITERS"])
    x = np.ones(262144, np.float32)  # 1MB
    os.environ["HOROVOD_RING_THRESHOLD"] = "0"
    vals = []
    for _ in range(3):
        hvd.allreduce(x, name="pr.hier", op=hvd.Sum)
    for r in range(rounds):
        hvd.barrier()
        t0 = time.perf_counter()
        for _ in range(iters):
            hvd.allreduce(x, name="pr.hier", op=hvd.Sum)
        vals.append((time.perf_counter() - t0) / iters)
        hvd.barrier()

    os.environ["HOROVOD_HIERARCHICAL_MODE"] = "leader"
    os.environ["HOROVOD_HIER_ARENA"] = "auto"
    iters16 = int(os.environ["PERF_TR_ITERS"])
    x16 = np.ones(4194304, np.float32)  # 16MB
    vals16 = []
    for _ in range(2):
        hvd.allreduce(x16, name="pr.hier16", op=hvd.Sum)
    # Fail loudly if the arena legs silently fell back to the per-pair
    # rings (capability bit not agreed): a rings measurement must never
    # be archived under the hier_arena label.
    assert hvd.metrics()["metrics"].get(
        "horovod_hier_arena_ops_total", 0) > 0, (
        "hier_arena stage measured the ring fallback — is shm "
        "writable and are the simulated hosts' slots co-located?")
    for r in range(rounds):
        hvd.barrier()
        t0 = time.perf_counter()
        for _ in range(iters16):
            hvd.allreduce(x16, name="pr.hier16", op=hvd.Sum)
        vals16.append((time.perf_counter() - t0) / iters16)
        hvd.barrier()
    rank = hvd.rank()
    hvd.shutdown()
    return {"rank": rank, "hier_1mb_s": vals,
            "hier_arena_16mb_s": vals16}


def _traced_worker():
    """np=2 traced-vs-eager gradient exchange (docs/running.md "Traced
    collectives"): order-alternated arms per round on the SAME ~2.4M
    param pytree — the eager engine's grouped allreduce (both ranks
    driving, steady names) vs the traced/XLA plane (a jitted shard_map
    grouped psum over rank 0's local 2-device mesh; peers hold at the
    barrier). Two stages land in the report: `traced_step_ms` (the
    tracked XLA-plane arm) and `traced_eager_step_ms` (the engine arm,
    riding along per the compression_none precedent so the report shows
    both planes' cost on THIS box)."""
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    rounds = int(os.environ["PERF_ROUNDS"])
    iters = int(os.environ["PERF_TR_ITERS"])
    r = hvd.rank()

    # The canonical benchmark pytree AND the traced-arm harness —
    # imported, not copied, so this stage always measures exactly what
    # the microbench and docs/running.md document.
    from examples.microbench_allreduce import (
        _make_grad_tree,
        build_traced_exchange,
    )

    leaves = list(_make_grad_tree(np).values())

    def timed_eager():
        hvd.barrier()
        t0 = time.perf_counter()
        for _ in range(iters):
            hvd.grouped_allreduce(leaves, name="pr.tra.eager",
                                  op=hvd.Average)
        dt = (time.perf_counter() - t0) / iters
        hvd.barrier()
        return dt

    run_traced = build_traced_exchange(np, leaves) if r == 0 else None

    def timed_traced():
        hvd.barrier()
        dt = 0.0
        if r == 0:
            t0 = time.perf_counter()
            for _ in range(iters):
                run_traced()
            dt = (time.perf_counter() - t0) / iters
        hvd.barrier()
        return dt

    timed_eager()  # warmup: negotiate the steady names
    timed_traced()
    eager_vals, traced_vals = [], []
    for rd in range(rounds):
        if rd % 2 == 0:
            eager_vals.append(timed_eager())
            traced_vals.append(timed_traced())
        else:
            traced_vals.append(timed_traced())
            eager_vals.append(timed_eager())
    rank = hvd.rank()
    hvd.shutdown()
    return {"rank": rank, "traced_step_s": traced_vals,
            "traced_eager_step_s": eager_vals}


def _zero_worker():
    """np=4 ZeRO-1 optimizer step (docs/running.md "ZeRO sharded
    optimizer state"): the eager ``DistributedOptimizer(zero=1)`` path
    on the canonical ~2.4M-param microbench pytree — grouped gradient
    allreduce, owned-segment adam update, updated-segment allgather —
    with steady collective names (``zero.grads`` / ``zero.updates``)
    so the response cache engages. Besides the timing rounds it
    reports the MEASURED per-rank optimizer-state bytes (max across
    ranks; the element-block cut keeps ranks within one block of each
    other) and the replicated equivalent — the (n-1)/n memory number
    the mode exists for."""
    import numpy as np

    import jax
    import optax

    import horovod_tpu as hvd

    hvd.init()
    rounds = int(os.environ["PERF_ROUNDS"])
    iters = int(os.environ["PERF_TR_ITERS"])

    from examples.microbench_allreduce import _make_grad_tree

    grads = _make_grad_tree(np)
    params = {k: np.zeros_like(v) for k, v in grads.items()}
    inner = optax.adam(1e-3)
    tx = hvd.DistributedOptimizer(inner, zero=1)
    state_box = [tx.init(params)]
    sharded = int(sum(np.asarray(l).nbytes
                      for l in jax.tree.leaves(state_box[0].inner)))
    sharded = max(hvd.allgather_object(sharded))
    replicated = int(sum(
        int(np.prod(s.shape, dtype=np.int64)) * np.dtype(s.dtype).itemsize
        for s in jax.tree.leaves(jax.eval_shape(inner.init, params))))

    def timed():
        hvd.barrier()
        t0 = time.perf_counter()
        for _ in range(iters):
            _, state_box[0] = tx.update(grads, state_box[0], params)
        dt = (time.perf_counter() - t0) / iters
        hvd.barrier()
        return dt

    timed()  # warmup: negotiate the steady names, fill the caches
    vals = [timed() for _ in range(rounds)]
    rank = hvd.rank()
    hvd.shutdown()
    return {"rank": rank, "zero_step_s": vals,
            "zero_state_bytes": sharded,
            "zero_state_replicated_bytes": replicated}


def _serving_worker():
    """np=2 serving round-trip: echo model over the SPMD round
    protocol, p50 of programmatic submit -> reply."""
    import horovod_tpu as hvd

    hvd.init()
    rounds = int(os.environ["PERF_ROUNDS"])
    n_req = int(os.environ["PERF_SERVE_REQS"])

    def model_fn(weights, payloads):
        return [p for p in payloads]

    rank = hvd.rank()
    if rank != 0:
        hvd.serving.serve(model_fn, weights={})
        hvd.shutdown()
        return {"rank": rank}

    import threading

    from horovod_tpu.serving import InferenceFrontend

    frontend = InferenceFrontend(port=None)
    vals = []

    def drive():
        for _ in range(rounds):
            lats = []
            for _ in range(n_req):
                t0 = time.perf_counter()
                req = frontend.submit(1.0)
                assert req is not None
                assert req.wait(timeout=60)
                lats.append(time.perf_counter() - t0)
            lats.sort()
            vals.append(_quantile(lats, 0.5))
        frontend.request_stop()

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    report = hvd.serving.serve(model_fn, weights={}, frontend=frontend,
                               tick_seconds=0.05)
    t.join(timeout=60)
    hvd.shutdown()
    return {"rank": 0, "serving_rtt_p50_s": vals, "rounds": report}


# ---------------------------------------------------------------------------
# Harness

def measure(rounds: int, quick: bool) -> dict:
    from horovod_tpu.common import telemetry
    from horovod_tpu.runner import run

    env = {
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_TCP_TIMEOUT_SECONDS": "120",
        "PERF_ROUNDS": str(rounds),
        "PERF_LAT_ITERS": "10" if quick else "30",
        "PERF_BW_ITERS": "3" if quick else "8",
        "PERF_TR_ITERS": "2" if quick else "4",
        "PERF_SERVE_REQS": "10" if quick else "30",
    }
    stages: dict = {}

    res = run(_engine_worker, np=2,
              extra_env=dict(env, HOROVOD_TRANSPORT="auto"))
    r0 = next(r for r in res if r["rank"] == 0)
    raw = r0["stages"]
    for name in ("latency_small_p50_s", "ring_1mb_s", "segring_1mb_s",
                 "reducescatter_16mb_s"):
        vals = raw[name]
        stages[name[:-2] + "_ms"] = {
            "unit": "ms",
            "rounds": [round(v * 1e3, 4) for v in vals],
            "value": round(_median(vals) * 1e3, 4),
        }
    tr = raw["transport_4mb_s"]
    for arm in ("tcp", "shm"):
        vals = [d[arm] for d in tr]
        stages[f"transport_{arm}_4mb_ms"] = {
            "unit": "ms",
            "rounds": [round(v * 1e3, 4) for v in vals],
            "value": round(_median(vals) * 1e3, 4),
        }
    # Wire compression (docs/running.md "Wire compression"):
    # `compression_16mb_ms` is the tracked bf16 arm; the none arm rides
    # along so the report shows the codec's cost/benefit on THIS box
    # (loopback has no wire to save — real NICs are where bf16 wins).
    cmp = raw["compression_16mb_s"]
    for arm, name in (("bf16", "compression_16mb_ms"),
                      ("none", "compression_none_16mb_ms")):
        vals = [d[arm] for d in cmp]
        stages[name] = {
            "unit": "ms",
            "rounds": [round(v * 1e3, 4) for v in vals],
            "value": round(_median(vals) * 1e3, 4),
        }
    # Native kernel A/B (docs/native.md): `native_ring_16mb_ms` is the
    # tracked arm (kernels on — what production runs); the numpy
    # fallback arm rides along so every report shows the port's win on
    # THIS box.
    nat = raw["native_ring_16mb_s"]
    for arm, name in (("on", "native_ring_16mb_ms"),
                      ("off", "native_off_ring_16mb_ms")):
        vals = [d[arm] for d in nat]
        stages[name] = {
            "unit": "ms",
            "rounds": [round(v * 1e3, 4) for v in vals],
            "value": round(_median(vals) * 1e3, 4),
        }

    os.environ["HVDRUN_FORCE_LOCAL"] = "1"
    res = run(_hier_worker, np=4, hosts="hostA:2,hostB:2",
              extra_env=dict(env, HVDRUN_FORCE_LOCAL="1",
                             HOROVOD_TRANSPORT="auto",
                             HOROVOD_HIERARCHICAL_ALLREDUCE="auto"))
    hier0 = next(r for r in res if r.get("rank") == 0)
    for key, name in (("hier_1mb_s", "hier_1mb_ms"),
                      ("hier_arena_16mb_s", "hier_arena_16mb_ms")):
        vals = hier0[key]
        stages[name] = {
            "unit": "ms",
            "rounds": [round(v * 1e3, 4) for v in vals],
            "value": round(_median(vals) * 1e3, 4),
        }

    res = run(_traced_worker, np=2,
              extra_env=dict(
                  env,
                  XLA_FLAGS="--xla_force_host_platform_device_count=2",
                  HOROVOD_TRANSPORT="auto"))
    tr0 = next(r for r in res if r.get("rank") == 0)
    for key, name in (("traced_step_s", "traced_step_ms"),
                      ("traced_eager_step_s", "traced_eager_step_ms")):
        vals = tr0[key]
        stages[name] = {
            "unit": "ms",
            "rounds": [round(v * 1e3, 4) for v in vals],
            "value": round(_median(vals) * 1e3, 4),
        }

    res = run(_zero_worker, np=4,
              extra_env=dict(env, HOROVOD_TRANSPORT="auto"))
    z0 = next(r for r in res if r.get("rank") == 0)
    vals = z0["zero_step_s"]
    stages["zero_step_ms"] = {
        "unit": "ms",
        "rounds": [round(v * 1e3, 4) for v in vals],
        "value": round(_median(vals) * 1e3, 4),
    }
    # State bytes are a memory measurement, not a timing: exact
    # integers, one round. Lower-is-better still holds — an
    # ownership-cut regression that grows a rank's shard trips the
    # gate like any slowdown.
    stages["zero_state_bytes"] = {
        "unit": "bytes",
        "rounds": [z0["zero_state_bytes"]],
        "value": z0["zero_state_bytes"],
        "replicated_bytes": z0["zero_state_replicated_bytes"],
    }

    res = run(_serving_worker, np=2, extra_env=env)
    vals = next(r for r in res if r.get("rank") == 0)["serving_rtt_p50_s"]
    stages["serving_rtt_p50_ms"] = {
        "unit": "ms",
        "rounds": [round(v * 1e3, 4) for v in vals],
        "value": round(_median(vals) * 1e3, 4),
    }

    return {
        "schema": SCHEMA,
        "kind": "horovod_perf_report",
        "time": time.time(),
        "build": telemetry.build_info(),
        "rounds": rounds,
        "quick": quick,
        "stages": stages,
    }


# ---------------------------------------------------------------------------
# Baseline comparison (pure — unit-tested on synthetic reports)

def compare(report: dict, baseline: dict,
            default_tolerance: float = DEFAULT_TOLERANCE) -> list:
    """Per-stage verdicts of `report` against `baseline`. Every stage
    is lower-is-better; regression iff ratio > 1 + tolerance
    (STRICTLY — the boundary passes). A stage the baseline names but
    the report lacks is `missing` (fails the gate: a silently dropped
    measurement must not read as a pass); NaN measurements are
    `invalid`; an unusable baseline entry is `skipped` (a broken
    baseline must not fail every future run); stages only the report
    has are `new` (informational)."""
    tolerances = baseline.get("tolerances", {})
    verdicts = []
    rep_stages = report.get("stages", {})
    base_stages = baseline.get("stages", {})
    for name in sorted(base_stages):
        tol = float(tolerances.get(name, default_tolerance))
        base_val = base_stages[name].get("value")
        ent = {"stage": name, "baseline": base_val, "tolerance": tol}
        if (not isinstance(base_val, (int, float)) or base_val <= 0
                or (isinstance(base_val, float) and math.isnan(base_val))):
            ent.update(status="skipped", value=None, ratio=None)
            verdicts.append(ent)
            continue
        rep = rep_stages.get(name)
        val = rep.get("value") if isinstance(rep, dict) else None
        if rep is None:
            ent.update(status="missing", value=None, ratio=None)
            verdicts.append(ent)
            continue
        if (not isinstance(val, (int, float))
                or (isinstance(val, float) and math.isnan(val))):
            ent.update(status="invalid", value=val, ratio=None)
            verdicts.append(ent)
            continue
        ratio = val / base_val
        ent.update(
            status="regression" if ratio > 1.0 + tol else "ok",
            value=val, ratio=round(ratio, 4))
        verdicts.append(ent)
    for name in sorted(set(rep_stages) - set(base_stages)):
        rep = rep_stages[name]
        verdicts.append({
            "stage": name, "status": "new",
            "value": rep.get("value") if isinstance(rep, dict) else None,
            "baseline": None, "ratio": None, "tolerance": None,
        })
    return verdicts


GATE_FAIL_STATES = ("regression", "missing", "invalid")


def gate_verdict(verdicts: list) -> bool:
    """True = pass. missing/invalid fail alongside regressions: a
    gate that can be passed by not measuring is not a gate."""
    return not any(v["status"] in GATE_FAIL_STATES for v in verdicts)


def render(verdicts: list) -> str:
    lines = [f"{'stage':<26} {'value':>12} {'baseline':>12} "
             f"{'ratio':>7} {'tol':>5}  status"]
    for v in verdicts:
        val = f"{v['value']:.3f}" if isinstance(
            v["value"], (int, float)) else "-"
        base = f"{v['baseline']:.3f}" if isinstance(
            v["baseline"], (int, float)) else "-"
        ratio = f"{v['ratio']:.3f}" if v["ratio"] is not None else "-"
        tol = f"{v['tolerance']:.2f}" if v["tolerance"] is not None else "-"
        lines.append(f"{v['stage']:<26} {val:>12} {base:>12} "
                     f"{ratio:>7} {tol:>5}  {v['status']}")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", help="write the measured report JSON here")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline report to compare against "
                         "(default: BENCH_BASELINE.json)")
    ap.add_argument("--gate", action="store_true",
                    help="exit 1 on regression/missing/invalid "
                         "(default: warn only)")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="default relative tolerance (baseline "
                         "`tolerances` map overrides per stage)")
    ap.add_argument("--rounds", type=int, default=3,
                    help="order-alternated measurement rounds")
    ap.add_argument("--quick", action="store_true",
                    help="fewer iterations per stage (CI budget)")
    ap.add_argument("--replay",
                    help="skip measurement; load stage values from this "
                         "existing report (gate self-tests)")
    ap.add_argument("--inject-slowdown", type=float, default=0.0,
                    help="multiply every measured stage value by this "
                         "factor after measurement — proves the gate "
                         "trips (self-test)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the measured report to the baseline path")
    args = ap.parse_args()

    if args.replay:
        with open(args.replay) as f:
            report = json.load(f)
    else:
        report = measure(args.rounds, args.quick)

    if args.inject_slowdown > 0:
        report = json.loads(json.dumps(report))  # deep copy
        for st in report["stages"].values():
            if isinstance(st.get("value"), (int, float)):
                st["value"] = st["value"] * args.inject_slowdown
        report["injected_slowdown"] = args.inject_slowdown

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)

    if args.update_baseline:
        with open(args.baseline, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        print(f"baseline updated: {args.baseline}")
        return 0

    if not os.path.exists(args.baseline):
        print(f"no baseline at {args.baseline}; report only")
        print(json.dumps(report["stages"], indent=1, sort_keys=True))
        return 0

    with open(args.baseline) as f:
        baseline = json.load(f)
    verdicts = compare(report, baseline, args.tolerance)
    print(render(verdicts))
    print(json.dumps({
        "metric": "perf_report",
        "build": report.get("build"),
        "gate": args.gate,
        "pass": gate_verdict(verdicts),
        "stages": {v["stage"]: v["status"] for v in verdicts},
    }))
    if not gate_verdict(verdicts):
        bad = [v for v in verdicts if v["status"] in GATE_FAIL_STATES]
        msg = ", ".join(f"{v['stage']}={v['status']}" for v in bad)
        if args.gate:
            print(f"PERF GATE FAILED: {msg}", file=sys.stderr)
            return 1
        print(f"perf regression WARNING (not gating): {msg}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
